"""PerfEvidence ledger: every perf measurement the repo produces, one store.

The MFU campaign's artifacts are scattered across formats that each grew
for one consumer: probe ladders (``PROBE_*.json``, including the
``ok:false`` watchdog rows a dead tunnel leaves behind), bench rounds
(``BENCH_*.json`` / ``BENCH_SERVE_*.json`` / ``BENCH_SESSION_*.json``),
``tools/mfu_lab.py`` tables, the kernel-autotune disk cache, the AOT
cache's per-program XLA ``cost_analysis`` stats (``PADDLE_AOT_STATS``),
per-rank runlogs, the serving flight recorder's step plans, and the
memory watcher's ring dumps (``profiler/memwatch.py``). This
module normalizes all of them into ONE schema-versioned JSONL ledger so
the profile-guided resolver (``tools/perf_resolve.py``) reads evidence
instead of re-profiling, and every flag decision can cite the row ids
that justify it.

Design rules:

  * **stdlib-only** — importable through the lint.py-style jax-free
    package bootstrap (``tools/`` consumers never pay a framework
    import). The only intra-package imports are ``profiler.instrument``
    (metrics, itself stdlib) and a *lazy, best-effort*
    ``aot.fingerprint.package_digest`` for the config fingerprint.
  * **rows are content-addressed** — ``id = <source>:<round>:<digest>``
    where the digest covers the normalized payload but NOT file mtimes,
    so rebuilding the ledger from the same committed artifacts in a
    fresh clone yields byte-identical ids (resolver determinism).
  * **malformed input is quarantined, never raised** — a torn JSONL
    line, a truncated artifact, or a wrong-schema row lands in
    ``Ledger.quarantined`` with its error; readers keep going.
  * **failure is first-class evidence** — a probe ``ok:false`` watchdog
    row ingests as a ``probe_failed`` row so the resolver knows the
    last hardware window died rather than silently trusting r04
    forever.

Row shape (schema 1)::

    {"schema": 1, "id": "probe:r04:ab12...", "source": "probe",
     "kind": "probe_step", "round": "r04", "ok": true,
     "device_kind": "TPU v5 lite", "topology": {...} | null,
     "config": {"flags": {...} | null, "package_digest": "..."|null},
     "file": "PROBE_r04.json", "mtime_utc": "...", "data": {...}}

The attribution half (:func:`roofline`, :func:`attribute_step`) joins
runlog wall times with per-program flops/bytes_accessed to decompose a
step into compute/collective/data/host fractions and place each program
on the roofline (compute- vs memory-bound) — the Ragged Paged Attention
paper's kernel-efficiency accounting applied to whole steps.
"""
from __future__ import annotations

import glob
import hashlib
import json
import os
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from . import instrument as _instr

__all__ = [
    "SCHEMA_VERSION", "SOURCES", "Ledger", "read_rows", "row_id",
    "make_row", "ingest_probe", "ingest_bench", "ingest_bench_serve",
    "ingest_bench_session", "ingest_mfu_lab", "ingest_autotune",
    "ingest_aot_stats", "ingest_runlog", "ingest_flight", "ingest_mem",
    "ingest_path",
    "scan_repo", "build_ledger", "round_order", "roofline",
    "attribute_step", "PEAK_BYTES_PER_S", "peak_flops_for_kind",
    "device_identity",
]

SCHEMA_VERSION = 1

#: every source tag a row may carry (perf_evidence_rows_total{source})
SOURCES = ("probe", "bench", "bench_serve", "bench_session", "mfu_lab",
           "autotune", "aot_stats", "runlog", "flight", "mem")

# -- peak tables (documented approximations; bench.py owns the flops side) ----
#: bf16 peak FLOP/s by device-kind substring (mirrors bench.peak_flops_per_chip
#: — duplicated here so the jax-free bootstrap path never imports bench).
PEAK_FLOPS = (
    ("v5 lite", 197e12), ("v5litepod", 197e12), ("v5e", 197e12),
    ("v5p", 459e12), ("v5", 459e12), ("v4", 275e12),
    ("v6", 918e12), ("trillium", 918e12), ("cpu", 1e12),
)

#: HBM bandwidth (bytes/s) by device-kind substring — the roofline's
#: memory ceiling. Public figures: v5e 819 GB/s, v5p 2765 GB/s,
#: v4 1228 GB/s, v6e 1640 GB/s. cpu is a nominal debug value.
PEAK_BYTES_PER_S = (
    ("v5 lite", 8.19e11), ("v5litepod", 8.19e11), ("v5e", 8.19e11),
    ("v5p", 2.765e12), ("v5", 2.765e12), ("v4", 1.228e12),
    ("v6", 1.64e12), ("trillium", 1.64e12), ("cpu", 5e10),
)


def _lookup_peak(table, device_kind: Optional[str]) -> Optional[float]:
    kind = (device_kind or "").lower()
    for sub, v in table:
        if sub in kind:
            return v
    return None


def peak_flops_for_kind(device_kind: Optional[str]) -> Optional[float]:
    return _lookup_peak(PEAK_FLOPS, device_kind)


def peak_bytes_for_kind(device_kind: Optional[str]) -> Optional[float]:
    return _lookup_peak(PEAK_BYTES_PER_S, device_kind)


def device_identity() -> Tuple[Optional[str], Optional[str]]:
    """(device_kind, platform) of the local backend, or (None, None) —
    the one best-effort jax probe shared by every perf-config consumer
    (flags.apply_perf_config, aot stats). Lazy and never raising: a
    perf layer must not make startup wait on (or die with) hardware."""
    try:
        import jax
        devices = jax.devices()
        if devices:
            return (getattr(devices[0], "device_kind", None),
                    devices[0].platform)
    except Exception:  # noqa: BLE001 — identity is metadata, not data
        pass
    return (None, None)


# -- row construction ---------------------------------------------------------
def _digest(payload) -> str:
    blob = json.dumps(payload, sort_keys=True, default=str).encode()
    return hashlib.blake2b(blob, digest_size=8).hexdigest()


def row_id(source: str, rnd: Optional[str], kind: str, file: str,
           data: Dict[str, Any]) -> str:
    """Content-addressed row id. Mtimes and ingest timestamps stay OUT of
    the digest: the same committed artifact must produce the same id in
    every clone (the resolver's byte-identical-output contract)."""
    return (f"{source}:{rnd or 'x'}:"
            f"{_digest({'kind': kind, 'file': file, 'data': data})}")


def _mtime_utc(path: str) -> Optional[str]:
    try:
        return time.strftime("%Y-%m-%dT%H:%M:%SZ",
                             time.gmtime(os.path.getmtime(path)))
    except OSError:
        return None


def _config_fingerprint(flags_map: Optional[Dict[str, Any]]
                        ) -> Dict[str, Any]:
    """Config-identity component for a row: the flag map the measurement
    ran under (when the artifact recorded one) plus the package source
    digest — reusing ``aot/fingerprint.py``'s component so evidence and
    AOT artifacts agree on what "same code" means. Best-effort: under
    the bare-package bootstrap the digest import can fail; evidence
    carries null rather than refusing to ingest."""
    pkg = None
    try:
        from ..aot.fingerprint import package_digest
        pkg = package_digest()
    except Exception:  # noqa: BLE001 — fingerprint is identity, not data
        pkg = None
    return {"flags": dict(sorted(flags_map.items())) if flags_map else None,
            "package_digest": pkg}


def make_row(source: str, kind: str, data: Dict[str, Any], *,
             file: str = "", rnd: Optional[str] = None, ok: bool = True,
             device_kind: Optional[str] = None,
             topology: Optional[Dict[str, Any]] = None,
             flags_map: Optional[Dict[str, Any]] = None,
             mtime_utc: Optional[str] = None) -> Dict[str, Any]:
    if source not in SOURCES:
        raise ValueError(f"unknown evidence source {source!r} "
                         f"(want one of {SOURCES})")
    return {
        "schema": SCHEMA_VERSION,
        "id": row_id(source, rnd, kind, file, data),
        "source": source,
        "kind": kind,
        "round": rnd,
        "ok": bool(ok),
        "device_kind": device_kind,
        "topology": topology,
        "config": _config_fingerprint(flags_map),
        "file": file,
        "mtime_utc": mtime_utc,
        "data": data,
    }


def round_order(rnd: Optional[str]) -> Tuple[int, str]:
    """Sort key for round tags: r01 < r04 < ... < 'latest'; unknown tags
    sort below every numbered round (deterministic, string-tiebroken)."""
    if rnd is None:
        return (-1, "")
    if rnd == "latest":
        return (1 << 30, rnd)
    if rnd.startswith("r"):
        try:
            return (int(rnd[1:]), rnd)
        except ValueError:
            pass
    return (-1, rnd)


def _round_from_name(path: str) -> Optional[str]:
    base = os.path.basename(path)
    stem = base.rsplit(".", 1)[0]
    for part in reversed(stem.split("_")):
        low = part.lower()
        if low == "latest":
            return "latest"
        if len(low) >= 2 and low[0] == "r" and low[1:].isdigit():
            return low
    return None


# -- the ledger ---------------------------------------------------------------
class _WriterLock:
    """Cross-process writer lock (``<ledger>.lock``, flock). Readers
    never take it (reads tolerate torn tails); writers serialize so a
    ``merge`` rewrite can never drop a concurrently appended line. On
    platforms without fcntl the lock degrades to a no-op."""

    def __init__(self, path: str):
        self._path = path + ".lock"
        self._f = None

    def __enter__(self):
        try:
            import fcntl
            self._f = open(self._path, "a")
            fcntl.flock(self._f.fileno(), fcntl.LOCK_EX)
        except Exception:  # noqa: BLE001 — locking is best-effort
            self._f = None
        return self

    def __exit__(self, *exc):
        if self._f is not None:
            try:
                self._f.close()
            except OSError:
                pass
        return False


class Ledger:
    """Atomic JSONL evidence store.

    ``merge()`` is the bulk path: under the writer lock, the file's
    existing CONTENT is preserved verbatim (lines that failed to parse
    stay on disk for postmortems — quarantine is a read-side judgment,
    not destruction) and only new rows are appended, via tmp+rename so
    a killed writer can never truncate the committed file.
    ``append_line()`` is the hot path (one locked ``write()`` of one
    line in append mode — what ``RunLog`` uses per step). Reading never
    raises on bad input: malformed lines and wrong-schema rows land in
    ``self.quarantined`` as ``{"line": n, "error": ..., "text": ...}``.
    """

    def __init__(self, path: str):
        self.path = path
        self.quarantined: List[Dict[str, Any]] = []

    # -- read ----------------------------------------------------------------
    def rows(self) -> List[Dict[str, Any]]:
        rows, self.quarantined = read_rows(self.path)
        return rows

    def ids(self) -> set:
        return {r["id"] for r in self.rows()}

    # -- write ---------------------------------------------------------------
    def merge(self, new_rows: Iterable[Dict[str, Any]]) -> int:
        """Dedupe-by-id merge with the tmp+rename discipline (same as
        bench/mfu_lab artifact writes). Returns rows actually added."""
        with _WriterLock(self.path):
            existing = self.rows()
            try:
                with open(self.path) as f:
                    content = f.read()
            except OSError:
                content = ""
            if content and not content.endswith("\n"):
                content += "\n"
            seen = {r["id"] for r in existing}
            added = []
            for row in new_rows:
                if row.get("id") not in seen:
                    seen.add(row["id"])
                    added.append(row)
            if not added:
                return 0
            tmp = f"{self.path}.tmp-{os.getpid()}"
            try:
                with open(tmp, "w") as f:
                    f.write(content)
                    for row in added:
                        f.write(json.dumps(row, sort_keys=True) + "\n")
                os.replace(tmp, self.path)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
        by_source: Dict[str, int] = {}
        for row in added:
            by_source[row["source"]] = by_source.get(row["source"], 0) + 1
        for source, n in sorted(by_source.items()):
            _instr.record_perf_evidence_rows(source, n)
        return len(added)

    def append_line(self, row: Dict[str, Any]) -> None:
        """Single-line append for per-step writers (RunLog): one write
        call per line, flushed — a concurrent reader sees whole lines or
        nothing, and a torn final line is quarantined by read_rows."""
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with _WriterLock(self.path):
            with open(self.path, "a") as f:
                f.write(json.dumps(row, sort_keys=True) + "\n")
                f.flush()
        _instr.record_perf_evidence_rows(row.get("source", "runlog"), 1)


def read_rows(path: str) -> Tuple[List[Dict[str, Any]],
                                  List[Dict[str, Any]]]:
    """Parse a ledger file -> (rows, quarantined). Missing file -> both
    empty. Never raises on content: unparseable lines, non-dict rows,
    wrong/missing schema versions, and rows without an id are
    quarantined with their line number and error."""
    rows: List[Dict[str, Any]] = []
    quarantined: List[Dict[str, Any]] = []
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError:
        return [], []
    for n, line in enumerate(lines, 1):
        if not line.strip():
            continue
        try:
            row = json.loads(line)
        except ValueError as e:
            quarantined.append({"line": n, "error": f"json: {e}",
                                "text": line[:200]})
            continue
        if not isinstance(row, dict):
            quarantined.append({"line": n, "error": "row is not an object",
                                "text": line[:200]})
        elif row.get("schema") != SCHEMA_VERSION:
            quarantined.append({"line": n,
                                "error": f"schema {row.get('schema')!r} != "
                                         f"{SCHEMA_VERSION}",
                                "text": line[:200]})
        elif not isinstance(row.get("id"), str) or not row["id"]:
            quarantined.append({"line": n, "error": "missing row id",
                                "text": line[:200]})
        else:
            rows.append(row)
    return rows, quarantined


# -- ingestors (one per artifact format; each returns normalized rows) --------
def _load_json(path: str):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _num(v) -> Optional[float]:
    """Tolerant numeric coercion for artifact payloads: a hand-edited
    or future-format value that is not a number must degrade the field,
    never raise out of an ingestor (module contract)."""
    try:
        return float(v)
    except (TypeError, ValueError):
        return None


def ingest_probe(path: str) -> List[Dict[str, Any]]:
    """PROBE_*.json — the hardware probe ladder. An ``ok:false`` payload
    (watchdog expiry, tunnel down) is a first-class ``probe_failed`` row:
    the resolver uses it to mark decisions as carried-from-an-older-
    window instead of silently fresh."""
    doc = _load_json(path)
    if not isinstance(doc, dict):
        return []
    rnd = _round_from_name(path)
    base = os.path.basename(path)
    mt = _mtime_utc(path)
    if not doc.get("ok"):
        data = {"error": str(doc.get("error", "unknown"))[:500]}
        return [make_row("probe", "probe_failed", data, file=base, rnd=rnd,
                         ok=False, device_kind=doc.get("device_kind"),
                         mtime_utc=mt)]
    dk = doc.get("device_kind")
    topo = {"platform": doc.get("platform"), "device_kind": dk}
    rows = []
    for tier, step in sorted((doc.get("steps") or {}).items()):
        if not isinstance(step, dict):
            continue
        data = {"tier": tier}
        for k, v in sorted(step.items()):
            if k == "ok":
                continue
            data[k] = str(v)[:500] if k == "error" else v
        rows.append(make_row("probe", "probe_step", data, file=base,
                             rnd=rnd, ok=bool(step.get("ok")),
                             device_kind=dk, topology=topo, mtime_utc=mt))
    return rows


def _bench_parsed_rows(parsed: Dict[str, Any], base: str,
                       rnd: Optional[str], mt: Optional[str]
                       ) -> List[Dict[str, Any]]:
    extra = parsed.get("extra") or {}
    src = extra.get("value_source") or {}
    dk = extra.get("device") or src.get("device")
    live = "error" not in extra and (_num(parsed.get("value")) or 0) > 0
    data = {
        "metric": parsed.get("metric"),
        "value": parsed.get("value"),
        "unit": parsed.get("unit"),
        "vs_baseline": parsed.get("vs_baseline"),
        "mfu": extra.get("mfu") or src.get("mfu"),
        "config": extra.get("config") or src.get("config"),
        "error": str(extra.get("error"))[:500] if extra.get("error")
        else None,
        "carried_from": src.get("file"),
    }
    rows = [make_row("bench", "train_throughput", data, file=base, rnd=rnd,
                     ok=live, device_kind=dk, mtime_utc=mt)]
    for tag, att in sorted((extra.get("attempts") or {}).items()):
        if not isinstance(att, dict):
            continue
        adata = {"tag": tag, "tps": att.get("tps"), "mfu": att.get("mfu"),
                 "error": str(att.get("error"))[:500]
                 if att.get("error") else None}
        rows.append(make_row("bench", "bench_attempt", adata, file=base,
                             rnd=rnd, ok=att.get("error") is None,
                             device_kind=dk, mtime_utc=mt))
    return rows


def ingest_bench(path: str) -> List[Dict[str, Any]]:
    """BENCH_rNN.json — the driver wrapper ({"n","cmd","rc","tail",
    "parsed"}) around one bench.py line. The parsed payload is the
    evidence; a value carried forward from an older session (tunnel
    down) ingests ok:false with the carried-from file recorded."""
    doc = _load_json(path)
    if not isinstance(doc, dict):
        return []
    parsed = doc.get("parsed")
    if not isinstance(parsed, dict):
        # a crashed round left only the traceback tail: that is still
        # evidence (the round produced no number)
        data = {"rc": doc.get("rc"),
                "tail": str(doc.get("tail", ""))[-500:]}
        return [make_row("bench", "bench_crashed", data,
                         file=os.path.basename(path),
                         rnd=_round_from_name(path), ok=False,
                         mtime_utc=_mtime_utc(path))]
    return _bench_parsed_rows(parsed, os.path.basename(path),
                              _round_from_name(path), _mtime_utc(path))


def ingest_bench_session(path: str) -> List[Dict[str, Any]]:
    """BENCH_SESSION_rNN.json — a successful hardware session (bench.py's
    own output, committed by the watcher). The train_session row is the
    MFU anchor perf_report diffs against."""
    doc = _load_json(path)
    if not isinstance(doc, dict) or "metric" not in doc:
        return []
    rows = _bench_parsed_rows(doc, os.path.basename(path),
                              _round_from_name(path), _mtime_utc(path))
    for row in rows:
        row_data = dict(row["data"])
        row["source"] = "bench_session"
        row["kind"] = ("train_session" if row["kind"] == "train_throughput"
                       else row["kind"])
        row["id"] = row_id("bench_session", row["round"], row["kind"],
                           row["file"], row_data)
    return rows


def ingest_bench_serve(path: str) -> List[Dict[str, Any]]:
    """BENCH_SERVE_*.json — serving bench (static vs continuous,
    spec vs nonspec). These run on CPU in CI, so device_kind stays
    null unless the artifact says otherwise — the resolver only emits
    decisions for rows with a known device."""
    doc = _load_json(path)
    if not isinstance(doc, dict) or doc.get("bench") != "serve":
        return []
    rnd = doc.get("tag") or _round_from_name(path)
    base = os.path.basename(path)
    mt = _mtime_utc(path)
    dk = doc.get("device_kind")
    common = {"model": doc.get("model"), "workload": doc.get("workload"),
              "engine": doc.get("engine"), "fast": doc.get("fast")}
    rows = []
    for mode in ("static", "continuous", "nonspec", "spec"):
        res = doc.get(mode)
        if not isinstance(res, dict):
            continue
        data = dict(common)
        data["mode"] = mode
        for k, v in sorted(res.items()):
            if isinstance(v, (int, float, str, bool, type(None))):
                data[k] = v
        rows.append(make_row("bench_serve", "serve_bench", data, file=base,
                             rnd=rnd, ok=True, device_kind=dk,
                             mtime_utc=mt))
    summary = {k: doc.get(k) for k in ("vs_static", "vs_nonspec")
               if doc.get(k) is not None}
    if summary:
        rows.append(make_row("bench_serve", "serve_summary", summary,
                             file=base, rnd=rnd, ok=True, device_kind=dk,
                             mtime_utc=mt))
    return rows


def rows_from_mfu_lab(results: Dict[str, Any], rnd: Optional[str],
                      base: str, mtime_utc: Optional[str] = None,
                      device_kind: Optional[str] = None
                      ) -> List[Dict[str, Any]]:
    """Normalize an in-memory mfu_lab results table (tag -> bench row).
    Shared by ingest_mfu_lab (committed MFU_LAB_*.json) and
    ``tools/mfu_lab.py --evidence`` (appends as it measures)."""
    rows = []
    for tag, res in sorted((results or {}).items()):
        if not isinstance(res, dict):
            continue
        extra = res.get("extra") or {}
        err = res.get("error") or extra.get("error")
        data = {"tag": tag, "tps": res.get("value"),
                "mfu": extra.get("mfu"),
                "pallas_fused": bool(extra.get("pallas_fused")),
                "from": res.get("from"),
                "wall_s": res.get("wall_s"),
                "error": str(err)[:500] if err else None}
        rows.append(make_row(
            "mfu_lab", "lab_rung", data, file=base, rnd=rnd,
            ok=err is None and bool(res.get("value")),
            device_kind=device_kind or extra.get("device"),
            mtime_utc=mtime_utc))
    return rows


def ingest_mfu_lab(path: str) -> List[Dict[str, Any]]:
    doc = _load_json(path)
    if not isinstance(doc, dict):
        return []
    return rows_from_mfu_lab(doc, _round_from_name(path),
                             os.path.basename(path), _mtime_utc(path))


def ingest_autotune(path: str, device_kind: Optional[str] = None
                    ) -> List[Dict[str, Any]]:
    """AUTOTUNE_CACHE.json — kernels/autotune.py's disk cache:
    {json[(kernel, *signature)]: [block_q, block_k]}. Real signatures
    ((sq, sk, head_dim, dtype, causal) — flash_attention._tune_signature)
    carry NO device element, so the caller supplies ``device_kind``:
    ``build_ledger`` passes the device of the newest successful probe in
    the same root (the probe is what wrote the cache). A device-kind-
    looking signature element still wins when present."""
    doc = _load_json(path)
    if not isinstance(doc, dict):
        return []
    base = os.path.basename(path)
    mt = _mtime_utc(path)
    rows = []
    for dkey, config in sorted(doc.items()):
        try:
            key = json.loads(dkey)
        except ValueError:
            continue
        if not isinstance(key, list) or not key:
            continue
        kernel, sig = str(key[0]), key[1:]
        dk = next((s for s in sig if isinstance(s, str) and
                   any(t in s.lower() for t in ("tpu", "cpu", "gpu", "v5",
                                                "v4", "v6"))), None) \
            or device_kind
        data = {"kernel": kernel, "signature": sig,
                "block": list(config) if isinstance(config, (list, tuple))
                else config}
        rows.append(make_row("autotune", "autotune_winner", data,
                             file=base, rnd=_round_from_name(path),
                             device_kind=dk, mtime_utc=mt))
    return rows


def ingest_aot_stats(path: str, device_kind: Optional[str] = None
                     ) -> List[Dict[str, Any]]:
    """PADDLE_AOT_STATS files — per-program hit/miss/fallback counts and
    the XLA cost_analysis (flops / bytes_accessed) aot/cache.py records
    at export. The cost rows are the attribution side's program table."""
    doc = _load_json(path)
    if not isinstance(doc, dict) or "programs" not in doc:
        return []
    base = os.path.basename(path)
    rnd = _round_from_name(path)
    mt = _mtime_utc(path)
    dk = doc.get("device_kind") or device_kind  # own stamp beats the hint
    rows = []
    for name, prog in sorted((doc.get("programs") or {}).items()):
        if not isinstance(prog, dict):
            continue
        data = {"program": name,
                "hits": prog.get("hits"), "misses": prog.get("misses"),
                "fallbacks": prog.get("fallbacks"),
                "cost": dict(prog["cost"]) if isinstance(prog.get("cost"),
                                                         dict) else None}
        if isinstance(prog.get("mem"), dict):
            # static memory footprint (aot/cache.py memory_analysis) —
            # added ONLY when present so pre-mem artifacts keep their
            # content-addressed row ids (ledger stability across rebuilds)
            data["mem"] = dict(prog["mem"])
        rows.append(make_row("aot_stats", "program_cost", data, file=base,
                             rnd=rnd, ok=data["cost"] is not None,
                             device_kind=dk, mtime_utc=mt))
    return rows


def ingest_runlog(path: str) -> List[Dict[str, Any]]:
    """runlog_rank*.jsonl — one runlog_meta row (flops/peak) plus ONE
    runlog_summary row (count, mean/last step time, last mfu): a 10k-step
    log must not become 10k ledger rows. Live per-step evidence goes
    through RunLog's own PADDLE_PERF_EVIDENCE append, not this."""
    base = os.path.basename(path)
    rnd = _round_from_name(path)
    mt = _mtime_utc(path)
    meta: Optional[Dict[str, Any]] = None
    steps: List[Dict[str, Any]] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail line: the summary still lands
                if rec.get("kind") == "meta":
                    meta = rec
                elif rec.get("kind") == "step":
                    steps.append(rec)
    except OSError:
        return []
    rows = []
    dk = (meta or {}).get("device_kind")
    if meta is not None:
        data = {"rank": meta.get("rank"), "world": meta.get("world"),
                "flops_per_step": meta.get("flops_per_step"),
                "peak_flops": meta.get("peak_flops")}
        rows.append(make_row("runlog", "runlog_meta", data, file=base,
                             rnd=rnd, device_kind=dk, mtime_utc=mt))
    if steps:
        times = [s["step_time_ms"] for s in steps
                 if isinstance(s.get("step_time_ms"), (int, float))]
        last = steps[-1]
        data = {"steps": len(steps),
                "mean_step_time_ms": (round(sum(times) / len(times), 3)
                                      if times else None),
                "last_step": {k: last.get(k) for k in
                              ("step", "step_time_ms", "loss", "tokens",
                               "tokens_per_s", "mfu")}}
        rows.append(make_row("runlog", "runlog_summary", data, file=base,
                             rnd=rnd, device_kind=dk, mtime_utc=mt))
    return rows


def ingest_flight(path: str) -> List[Dict[str, Any]]:
    """Serving flight-recorder dumps (serving/obs.py): one step_plan row
    summarizing the ring — why the dump fired, the last step's plan
    (budget split / admission / pool / spec outcome), and the SLO
    snapshot at dump time."""
    doc = _load_json(path)
    if not isinstance(doc, dict) or "steps" not in doc or \
            "reason" not in doc:
        return []
    steps = doc.get("steps") or []
    tel = doc.get("telemetry") or {}
    # best-effort ingest of foreign-generation dumps: the evidence
    # plane reports whatever a partial/older record carries and must
    # never crash on it — the blessed exception to "required keys are
    # read with []" (WIR103), scoped to exactly these two reads
    reason = doc.get("reason")  # tpu-lint: disable=WIR103
    detail = doc.get("detail")  # tpu-lint: disable=WIR103
    data = {"reason": reason,
            "detail": detail,
            "buffered_steps": len(steps),
            "last_step": steps[-1] if steps else None,
            "slo": tel.get("slo"),
            "requests": tel.get("requests")}
    return [make_row("flight", "step_plan", data,
                     file=os.path.basename(path),
                     rnd=_round_from_name(path),
                     ok=reason == "manual",
                     mtime_utc=_mtime_utc(path))]


def ingest_mem(path: str) -> List[Dict[str, Any]]:
    """Memory-watcher dumps (profiler/memwatch.py): one ``mem_snapshot``
    row summarizing the ring — why the dump fired, the last snapshot's
    pool split, and the high watermarks. ``tools/mem_report.py`` joins
    these with the AOT ``memory_analysis`` rows into the per-chip
    budget breakdown. An anomaly-triggered dump (near_oom) ingests
    ``ok: false`` — pressure is failure evidence, same convention as
    the serving flight recorder's rows."""
    doc = _load_json(path)
    if not isinstance(doc, dict) or doc.get("kind") != "memwatch" or \
            "steps" not in doc:
        return []
    steps = doc.get("steps") or []
    last = steps[-1] if steps else None
    data = {"reason": doc.get("reason"),
            "detail": doc.get("detail"),
            "buffered_steps": len(steps),
            "last": last,
            "watermarks": doc.get("watermarks"),
            "counters": doc.get("counters")}
    return [make_row("mem", "mem_snapshot", data,
                     file=os.path.basename(path),
                     rnd=_round_from_name(path),
                     ok=doc.get("reason") == "manual",
                     device_kind=doc.get("device_kind"),
                     mtime_utc=_mtime_utc(path))]


#: (glob pattern, ingestor) in scan order. BENCH_SESSION must come before
#: the BENCH_r* pattern would otherwise swallow it.
_SCAN = (
    ("PROBE_*.json", ingest_probe),
    ("BENCH_SESSION_*.json", ingest_bench_session),
    ("BENCH_SERVE_*.json", ingest_bench_serve),
    ("BENCH_r*.json", ingest_bench),
    ("MFU_LAB_*.json", ingest_mfu_lab),
    ("AUTOTUNE_CACHE.json", ingest_autotune),
    ("AOT_STATS_*.json", ingest_aot_stats),
    ("aot_stats_*.json", ingest_aot_stats),
    ("runlog_rank*.jsonl", ingest_runlog),
    ("flight_*.json", ingest_flight),
    ("FLIGHT_*.json", ingest_flight),
    ("memwatch_*.json", ingest_mem),
    ("MEM_WATCH_*.json", ingest_mem),
)


def ingest_path(path: str, device_hint: Optional[str] = None
                ) -> List[Dict[str, Any]]:
    """Dispatch one artifact file to its ingestor by filename pattern.
    ``device_hint`` flows to the ingestors whose artifacts carry no
    device identity of their own (the autotune cache; AOT stats files
    predating the device_kind stamp)."""
    import fnmatch
    base = os.path.basename(path)
    for pattern, fn in _SCAN:
        if fnmatch.fnmatchcase(base, pattern):
            if fn in (ingest_autotune, ingest_aot_stats):
                return fn(path, device_hint)
            return fn(path)
    return []


def scan_repo(root: str) -> List[str]:
    """Committed perf artifacts at the repo root, in deterministic order."""
    out = []
    for pattern, _ in _SCAN:
        out.extend(sorted(glob.glob(os.path.join(root, pattern))))
    seen = set()
    uniq = []
    for p in out:
        if p not in seen:
            seen.add(p)
            uniq.append(p)
    return uniq


def build_ledger(root: str, out_path: str,
                 extra_paths: Iterable[str] = ()
                 ) -> Tuple["Ledger", Dict[str, int]]:
    """Ingest every committed artifact under ``root`` (plus any
    ``extra_paths``) into the ledger at ``out_path`` (atomic merge).
    Returns (ledger, {basename: rows_ingested})."""
    ledger = Ledger(out_path)
    report: Dict[str, int] = {}
    rows: List[Dict[str, Any]] = []
    paths = list(scan_repo(root)) + [p for p in extra_paths if p]
    # device hint for device-less artifacts (the autotune cache): the
    # newest successful probe in this root is what wrote them
    hint = None
    hint_key = (-1, "")
    for path in paths:
        if os.path.basename(path).startswith("PROBE_"):
            doc = _load_json(path)
            if isinstance(doc, dict) and doc.get("ok") and \
                    doc.get("device_kind"):
                key = round_order(_round_from_name(path))
                if key > hint_key:
                    hint, hint_key = doc["device_kind"], key
    for path in paths:
        got = ingest_path(path, device_hint=hint)
        report[os.path.basename(path)] = len(got)
        rows.extend(got)
    ledger.merge(rows)
    return ledger, report


# -- step-time anatomy / roofline attribution ---------------------------------
def roofline(cost: Dict[str, Any], peak_flops: float,
             peak_bytes_per_s: Optional[float] = None) -> Dict[str, Any]:
    """Place one program's XLA cost_analysis on the roofline.

    intensity = flops / bytes_accessed; machine_balance = peak_flops /
    peak_bandwidth. ratio = intensity / machine_balance: >= 1 means the
    program has enough arithmetic per byte to be compute-bound on this
    device; < 1 means the memory system is the ceiling. Without a
    bandwidth figure only the modeled compute time is returned."""
    flops = float(cost.get("flops") or 0.0)
    nbytes = float(cost.get("bytes_accessed") or 0.0)
    out: Dict[str, Any] = {
        "flops": flops,
        "bytes_accessed": nbytes,
        "compute_s": flops / peak_flops if peak_flops else None,
        "memory_s": (nbytes / peak_bytes_per_s
                     if peak_bytes_per_s and nbytes else None),
        "intensity": flops / nbytes if nbytes else None,
        "machine_balance": (peak_flops / peak_bytes_per_s
                            if peak_bytes_per_s and peak_flops else None),
        "ratio": None,
        "bound": None,
    }
    if out["intensity"] is not None and out["machine_balance"]:
        out["ratio"] = out["intensity"] / out["machine_balance"]
        out["bound"] = "compute" if out["ratio"] >= 1.0 else "memory"
    modeled = [t for t in (out["compute_s"], out["memory_s"])
               if t is not None]
    out["modeled_s"] = max(modeled) if modeled else None
    return out


def attribute_step(wall_s: float, costs: Dict[str, Dict[str, Any]],
                   peak_flops: float,
                   peak_bytes_per_s: Optional[float] = None,
                   collective_s: float = 0.0, data_s: float = 0.0,
                   emit_metrics: bool = False) -> Dict[str, Any]:
    """Decompose one step's wall time into compute/collective/data/host.

    ``costs`` maps program name -> cost_analysis dict ({"flops",
    "bytes_accessed"}). The device (compute) component is the roofline
    envelope max(flops/peak_flops, bytes/peak_bw) summed over programs;
    collective_s and data_s are caller-measured (step-plan records /
    dataloader spans); host is the unmodeled remainder, floored at 0.
    Fractions are normalized over the component SUM (not wall) so they
    always total 1.0 even when the model overcommits a short wall time.

    With ``emit_metrics`` the fractions and per-program roofline ratios
    are published through ``instrument.record_perf_*`` (no-ops while the
    metrics plane is disabled)."""
    wall_s = float(wall_s)
    programs = {name: roofline(cost, peak_flops, peak_bytes_per_s)
                for name, cost in sorted((costs or {}).items())}
    device_s = sum(p["modeled_s"] or 0.0 for p in programs.values())
    flops = sum(p["flops"] for p in programs.values())
    collective_s = max(float(collective_s), 0.0)
    data_s = max(float(data_s), 0.0)
    host_s = max(wall_s - device_s - collective_s - data_s, 0.0)
    total = device_s + collective_s + data_s + host_s
    fractions = {
        "compute": device_s / total if total else 0.0,
        "collective": collective_s / total if total else 0.0,
        "data": data_s / total if total else 0.0,
        "host": host_s / total if total else 0.0,
    }
    out = {
        "wall_s": wall_s,
        "device_s": device_s,
        "collective_s": collective_s,
        "data_s": data_s,
        "host_s": host_s,
        "fractions": fractions,
        "programs": programs,
        "mfu": (flops / (wall_s * peak_flops)
                if wall_s > 0 and peak_flops else None),
    }
    if emit_metrics:
        for component, frac in sorted(fractions.items()):
            _instr.record_perf_step_fraction(component, frac)
        for name, p in programs.items():
            if p["ratio"] is not None:
                _instr.record_perf_roofline(name, p["ratio"])
    return out
