"""Profiler + observability plane.

Reference parity: python/paddle/profiler/ (Profiler profiler.py:358 with
states CLOSED/READY/RECORD/RECORD_AND_RETURN, ProfilerTarget, RecordEvent
utils.py:47, make_scheduler, chrome-trace export, summary tables) wrapping
the C++ host tracer + CUPTI (fluid/platform/profiler/).

TPU-native: host-side annotations are recorded in-process into ONE shared,
lock-guarded buffer (spans may begin/end on any thread — dataloader worker
spans are collected too); the framework emits spans per dispatched op, per
train/eval phase (Forward/Backward/Optimization/Dataloader), and per
collective entry point, all guarded by a single boolean so disabled runs
pay one check. Device-side tracing delegates to jax.profiler (XLA's TPU
trace), the platform's CUPTI equivalent.

Exports: chrome-trace JSON with rank-qualified pids, process/thread-name
metadata and a wall-clock anchor (``tools/trace_merge.py`` merges N ranks
into one timeline); protobuf wire format (``export_protobuf``); summary
tables (``Profiler.summary`` honoring ``SortedKeys``).

Beyond tracing, this package is the metrics plane (``profiler.metrics``:
Counter/Gauge/Histogram registry with JSON + Prometheus text exporters,
framework built-ins in ``profiler.instrument``) and the structured run log
(``profiler.runlog``: per-rank JSONL step records with step time, loss,
tokens/s and a FLOPs-based MFU estimate).
"""
from __future__ import annotations

import json
import os
import threading
import time
from enum import Enum
from typing import Callable, List, Optional

from . import evidence, instrument, memwatch, metrics  # noqa: F401
from . import runlog  # noqa: F401 (re-export)
from .memwatch import (MemoryWatcher, MemWatchConfig,  # noqa: F401
                       resolve_watcher)
from .metrics import (Counter, Gauge, Histogram,  # noqa: F401
                      MetricsRegistry, disable_metrics, enable_metrics,
                      get_registry, metrics_enabled, reset_registry)
from .runlog import RunLog, model_flops_per_step, read_runlog  # noqa: F401

CLOCK_ANCHOR_EVENT = "paddle_tpu.clock_anchor"


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM_DEVICE = 3
    TPU = 4


class TracerEventType(Enum):
    Operator = 0
    Dataloader = 1
    ProfileStep = 2
    Forward = 3
    Backward = 4
    Optimization = 5
    Communication = 6
    PythonOp = 7
    UserDefined = 8


class _HostTracer:
    """Process-wide span buffer. NOT thread-local: spans begun on worker
    threads (dataloader, async checkpoint) land in the same lock-guarded
    list the profiler collects from — the old per-thread buffers silently
    dropped every worker-thread span."""

    __slots__ = ("enabled", "events", "lock")

    def __init__(self):
        self.enabled = False
        self.events: List[dict] = []
        self.lock = threading.Lock()


_tracer = _HostTracer()


def _now_us() -> float:
    return time.perf_counter_ns() / 1000.0


_pid_cell: List[Optional[int]] = [None]


def _trace_pid() -> int:
    """Rank-qualified pid: the global rank in multi-rank jobs (so merged
    timelines get one track per rank), the OS pid otherwise."""
    if _pid_cell[0] is None:
        from ..distributed.host_collectives import world_info
        rank, world = world_info()
        _pid_cell[0] = rank if world > 1 else os.getpid()
    return _pid_cell[0]


class RecordEvent:
    """Context manager / start-end span (parity: profiler/utils.py:47)."""

    def __init__(self, name: str,
                 event_type: TracerEventType = TracerEventType.UserDefined):
        self.name = name
        self.event_type = event_type
        self._begin = None

    def begin(self):
        # off path: one boolean check, no clock read
        self._begin = _now_us() if _tracer.enabled else None

    def end(self):
        if self._begin is None or not _tracer.enabled:
            self._begin = None
            return
        ev = {
            "name": self.name, "cat": self.event_type.name, "ph": "X",
            "ts": self._begin, "dur": _now_us() - self._begin,
            "pid": _trace_pid(), "tid": threading.get_ident() % 100000,
        }
        with _tracer.lock:
            _tracer.events.append(ev)
        self._begin = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


def make_scheduler(*, closed: int, ready: int, record: int, repeat: int = 0,
                   skip_first: int = 0) -> Callable[[int], ProfilerState]:
    """State machine over step numbers (parity: profiler.make_scheduler)."""
    period = closed + ready + record

    def scheduler(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD
    return scheduler


def _chrome_payload(events: List[dict]) -> dict:
    """Chrome-trace JSON body: spans + process/thread-name metadata
    (ph:"M") + a wall-clock anchor instant event so multi-rank traces can
    be aligned by tools/trace_merge.py. displayTimeUnit makes Perfetto
    render ms instead of raw microsecond ticks."""
    from ..distributed.host_collectives import world_info
    rank, world = world_info()
    meta: List[dict] = []
    seen_pids, seen_tids = set(), set()
    for e in events:
        pid = e.get("pid", 0)
        if pid not in seen_pids:
            seen_pids.add(pid)
            pname = f"rank {rank} (paddle_tpu)" if world > 1 \
                else f"paddle_tpu host {pid}"
            meta.append({"name": "process_name", "ph": "M", "pid": pid,
                         "args": {"name": pname}})
            meta.append({"name": "process_sort_index", "ph": "M", "pid": pid,
                         "args": {"sort_index": rank}})
        tkey = (pid, e.get("tid", 0))
        if tkey not in seen_tids:
            seen_tids.add(tkey)
            meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                         "tid": tkey[1],
                         "args": {"name": f"thread {tkey[1]}"}})
    anchor_pid = next(iter(seen_pids)) if seen_pids else _trace_pid()
    anchor = {"name": CLOCK_ANCHOR_EVENT, "ph": "i", "s": "g",
              "pid": anchor_pid, "tid": 0, "ts": _now_us(),
              "args": {"unix_time_us": time.time() * 1e6, "rank": rank}}
    return {"traceEvents": meta + [anchor] + list(events),
            "displayTimeUnit": "ms"}


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    """on_trace_ready callback writing chrome://tracing JSON."""
    def handler(prof: "Profiler"):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"host_{os.getpid()}"
        path = os.path.join(dir_name, f"{name}_{prof._export_seq}.json")
        prof._export_seq += 1
        with open(path, "w") as f:
            json.dump(_chrome_payload(prof._events), f)
        prof.last_export_path = path
    return handler


class Profiler:
    """Parity: paddle.profiler.Profiler (profiler.py:358).

    with Profiler(targets=[...], scheduler=(2, 5)) as p:
        for batch: train(); p.step()
    """

    def __init__(self, *, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only: bool = False, record_shapes: bool = False,
                 profile_memory: bool = False, with_flops: bool = False):
        self.targets = list(targets or [ProfilerTarget.CPU])
        if scheduler is None:
            self._scheduler = lambda step: ProfilerState.RECORD
        elif isinstance(scheduler, tuple):
            start, end = scheduler
            self._scheduler = make_scheduler(closed=max(start, 0), ready=0,
                                             record=end - start, repeat=1)
        else:
            self._scheduler = scheduler
        self.on_trace_ready = on_trace_ready
        self.timer_only = timer_only
        self._step = 0
        self._state = ProfilerState.CLOSED
        self._events: List[dict] = []
        self._export_seq = 0
        self.last_export_path = None
        self._step_times: List[float] = []
        self._last_step_ts = None
        self._jax_trace_dir = None

    # -- lifecycle ------------------------------------------------------------
    def start(self):
        self._state = self._scheduler(self._step)
        self._apply_state()

    def stop(self):
        if self._state in (ProfilerState.RECORD,
                           ProfilerState.RECORD_AND_RETURN):
            self._collect()
            self._finish_record()
        self._state = ProfilerState.CLOSED
        _tracer.enabled = False

    def step(self, num_samples: Optional[int] = None):
        now = _now_us()
        if self._last_step_ts is not None:
            self._step_times.append((now - self._last_step_ts) / 1000.0)
        self._last_step_ts = now
        prev = self._state
        if prev in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
            self._collect()
        self._step += 1
        self._state = self._scheduler(self._step)
        if prev == ProfilerState.RECORD_AND_RETURN or (
                prev == ProfilerState.RECORD
                and self._state not in (ProfilerState.RECORD,
                                        ProfilerState.RECORD_AND_RETURN)):
            self._finish_record()
        self._apply_state()

    def _apply_state(self):
        recording = self._state in (ProfilerState.RECORD,
                                    ProfilerState.RECORD_AND_RETURN)
        if recording and not _tracer.enabled:
            with _tracer.lock:
                _tracer.events = []
            _tracer.enabled = True
            if not self.timer_only and (
                    ProfilerTarget.TPU in self.targets
                    or ProfilerTarget.GPU in self.targets):
                self._start_device_trace()
        elif not recording and _tracer.enabled:
            _tracer.enabled = False

    def _start_device_trace(self):
        if self._jax_trace_dir is not None:
            return
        import tempfile

        import jax
        self._jax_trace_dir = tempfile.mkdtemp(prefix="paddle_tpu_trace_")
        try:
            jax.profiler.start_trace(self._jax_trace_dir)
        except Exception:
            self._jax_trace_dir = None

    def _collect(self):
        with _tracer.lock:
            collected = _tracer.events
            _tracer.events = []
        self._events.extend(collected)

    def _finish_record(self):
        if self._jax_trace_dir is not None:
            import jax
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._jax_trace_dir = None
        if self.on_trace_ready is not None:
            self.on_trace_ready(self)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- results --------------------------------------------------------------
    def export(self, path: str, format: str = "json"):
        with open(path, "w") as f:
            json.dump(_chrome_payload(self._events), f)

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms") -> str:
        """Render the per-name table, sorted per ``sorted_by`` (a
        ``SortedKeys``; GPU* keys alias their CPU counterparts — host spans
        are the only timed events here). Returns the rendered table."""
        by_name = {}
        for e in self._events:
            d = by_name.setdefault(e["name"], {"calls": 0, "total_us": 0.0,
                                               "max_us": 0.0,
                                               "min_us": float("inf")})
            d["calls"] += 1
            d["total_us"] += e["dur"]
            d["max_us"] = max(d["max_us"], e["dur"])
            d["min_us"] = min(d["min_us"], e["dur"])
        sort_key = {
            SortedKeys.CPUTotal: lambda d: d["total_us"],
            SortedKeys.GPUTotal: lambda d: d["total_us"],
            SortedKeys.CPUAvg: lambda d: d["total_us"] / max(d["calls"], 1),
            SortedKeys.GPUAvg: lambda d: d["total_us"] / max(d["calls"], 1),
            SortedKeys.CPUMax: lambda d: d["max_us"],
            SortedKeys.GPUMax: lambda d: d["max_us"],
            SortedKeys.CPUMin: lambda d: d["min_us"],
            SortedKeys.GPUMin: lambda d: d["min_us"],
        }.get(sorted_by, lambda d: d["total_us"])
        rows = sorted(by_name.items(), key=lambda kv: -sort_key(kv[1]))
        div, unit = {"s": (1e6, "s"), "ms": (1e3, "ms"),
                     "us": (1.0, "us")}.get(time_unit, (1e3, "ms"))
        lines = [f"{'name':<40} {'calls':>8} {f'total({unit})':>14} "
                 f"{f'avg({unit})':>12} {f'max({unit})':>12} "
                 f"{f'min({unit})':>12}"]
        for name, d in rows[:50]:
            lines.append(
                f"{name:<40} {d['calls']:>8} {d['total_us'] / div:>14.3f} "
                f"{d['total_us'] / max(d['calls'], 1) / div:>12.3f} "
                f"{d['max_us'] / div:>12.3f} {d['min_us'] / div:>12.3f}")
        text = "\n".join(lines)
        print(text)
        return text

    def step_info(self, unit=None) -> str:
        if not self._step_times:
            return "no steps recorded"
        import numpy as np
        div, u = {"s": (1e3, "s"), "ms": (1.0, "ms"),
                  "us": (1e-3, "us")}.get(unit or "ms", (1.0, "ms"))
        arr = np.asarray(self._step_times) / div
        return (f"steps: {len(arr)}, avg: {arr.mean():.3f} {u}, "
                f"p50: {np.percentile(arr, 50):.3f} {u}, "
                f"p99: {np.percentile(arr, 99):.3f} {u}")


def host_tracing_enabled() -> bool:
    return _tracer.enabled


def load_profiler_result(path: str):
    with open(path) as f:
        return json.load(f)


class SortedKeys(Enum):
    """Parity: paddle.profiler.SortedKeys — summary table sort orders."""
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


class SummaryView(Enum):
    """Parity: paddle.profiler.SummaryView."""
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8


def _pb_varint(v: int) -> bytes:
    out = b""
    v &= (1 << 64) - 1
    while True:
        b7 = v & 0x7F
        v >>= 7
        if v:
            out += bytes([b7 | 0x80])
        else:
            return out + bytes([b7])


def _pb_field(num: int, wire: int, payload: bytes) -> bytes:
    return _pb_varint((num << 3) | wire) + payload


def export_protobuf(dir_name: str, worker_name: Optional[str] = None):
    """Parity: paddle.profiler.export_protobuf — on_trace_ready callback
    serializing the trace in protobuf wire format:

      message Event { string name=1; uint64 start_us=2; uint64 end_us=3;
                      string cat=4; uint32 pid=5; uint32 tid=6; }
      message Trace { repeated Event events=1; }
    """
    def handler(prof: "Profiler"):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"host_{os.getpid()}"
        path = os.path.join(dir_name, f"{name}_{prof._export_seq}.pb")
        prof._export_seq += 1
        blob = b""
        for e in prof._events:
            nm = str(e.get("name", "")).encode()
            ev = _pb_field(1, 2, _pb_varint(len(nm)) + nm)
            start = int(e.get("ts", 0))
            dur = int(e.get("dur", 0))
            ev += _pb_field(2, 0, _pb_varint(start))
            ev += _pb_field(3, 0, _pb_varint(start + dur))
            cat = str(e.get("cat", e.get("ph", ""))).encode()
            ev += _pb_field(4, 2, _pb_varint(len(cat)) + cat)
            ev += _pb_field(5, 0, _pb_varint(int(e.get("pid", 0))))
            ev += _pb_field(6, 0, _pb_varint(int(e.get("tid", 0))))
            blob += _pb_field(1, 2, _pb_varint(len(ev)) + ev)
        with open(path, "wb") as f:
            f.write(blob)
        prof.last_export_path = path
    return handler
