"""Framework built-in metrics: the stable, greppable catalog.

Every instrumented call site in the framework funnels through one
``record_*`` helper here, each of which starts with the single-boolean
enabled check (``metrics._ENABLED[0]``) so disabled runs pay nothing
beyond that check. Metric families live on the default registry and are
created lazily on first record.

Catalog (names are a stable API — see README "Observability"):

  ops_dispatch_total{op}                 ops/dispatch.py, per dispatched op
  jit_compile_total{fn}                  jit/ — fresh traces (cache misses)
  jit_cache_hits_total{fn}               jit/ — compiled calls reusing a trace
  jit_compile_seconds                    wall time of calls that traced
  collective_calls_total{op,tier}        distributed/communication.py
  collective_bytes_total{op,tier}        payload bytes (tier: ici|host|identity)
  host_collective_rounds_total{op}       distributed/host_collectives.py
  host_collective_bytes_total{op}        store-routed payload bytes
  checkpoint_save_seconds                distributed/checkpoint.py
  checkpoint_load_seconds                distributed/checkpoint.py
  watchdog_ticks_total                   distributed/watchdog.py StepWatchdog
  watchdog_fires_total                   hang events fired
  train_steps_total                      engine/hapi training steps
  dataloader_batches_total               hapi fit/eval loader batches
  resilience_faults_injected_total{site,kind}  resilience/chaos.py probes
  resilience_retries_total{site}         resilience/retry.py retried attempts
  resilience_giveups_total{site}         retry budget exhausted (raise)
  resilience_ckpt_events_total{event}    corrupt_detected|fallback|gc
  resilience_guard_events_total{kind,action}   StepGuard nan/spike events
  resilience_preemptions_total{source}   resilience/preempt.py notices
  resilience_emergency_save_seconds      preemption emergency-save wall time
  checkpoint_async_queue_depth           in-flight async writer threads
  checkpoint_async_join_seconds          async writer join (drain) latency
  serve_queue_depth                      serving/engine.py waiting requests
  serve_running_seqs                     sequences in the continuous batch
  serve_admitted_total                   requests admitted to the batch
  serve_finished_total                   requests finished and evicted
  serve_preempted_total                  requests preempted under pool pressure
  serve_steps_total                      engine steps (device calls)
  serve_tokens_total                     tokens sampled across all requests
  serve_kv_pool_utilization              live KV pages / pool size (0..1)
  serve_prefix_cache_queries_total       serving/kv_pool.py prefix lookups
  serve_prefix_cache_hits_total          lookups that reused >= 1 page
  serve_ttft_seconds                     submit -> first token latency
  serve_token_seconds                    per-token (step) latency
  serve_spec_proposed_tokens_total       draft tokens fed to verify steps
  serve_spec_accepted_tokens_total       drafts confirmed by greedy verify
  serve_spec_accept_rate                 per-step accepted/proposed ratio
  serve_spec_rollback_pages_total        KV pages released rolling back drafts
  serve_slo_violations_total{kind}       serving/obs.py deadline misses (ttft|tpot)
  serve_slo_attainment                   SLO-tracked requests meeting deadlines (0..1)
  serve_goodput_tokens_total             tokens from requests that met their SLOs
  serve_flight_dumps_total{trigger}      flight-recorder dumps by trigger reason
  serve_ttft_quantile_seconds{q}         streaming TTFT sketch quantiles (p50|p95|p99)
  serve_tpot_quantile_seconds{q}         streaming per-output-token quantiles
  serve_e2e_quantile_seconds{q}          streaming end-to-end latency quantiles
  aot_cache_hits_total{program}          aot/cache.py artifact deserialized
  aot_cache_misses_total{program}        traced+exported fresh (published)
  aot_cache_load_seconds                 deserialize+ready wall time on a hit
  aot_cache_export_seconds               trace+export+publish wall time
  aot_cache_fallbacks_total{reason}      corrupt|chaos|io|deserialize|export|run
  perf_evidence_rows_total{source}       profiler/evidence.py ledger ingests
  perf_resolver_decisions_total{flag,status}  flags.apply_perf_config outcomes
  perf_step_fraction{component}          step-time anatomy (compute|collective|data|host)
  perf_program_roofline_ratio{program}   intensity / machine balance per program
  mem_bytes_in_use{pool}                 profiler/memwatch.py pool split + total
  mem_peak_bytes{pool}                   per-pool high watermarks (resettable)
  mem_watermark_fraction                 bytes_in_use / bytes_limit (0..1)
  mem_pressure_dumps_total{trigger}      memwatch ring dumps (near_oom|manual)
  serve_kv_pool_bytes                    device bytes of live sequences' KV pages
  serve_step_faults_total{kind}          serving/resilience.py contained step faults
  serve_request_retries_total{reason}    requests requeued for recompute after a fault
  serve_shed_total{policy}               submissions refused by admission control
  serve_drain_seconds                    graceful-drain wall time (notice -> manifest)
  serve_engine_restarts_total            drain manifests replayed into a fresh engine
  serve_router_routed_total{policy}      serving/router.py routing decisions by policy
  serve_router_affinity_hits_total       submissions routed to a prefix-affine replica
  serve_router_replica_queue_depth{replica}  per-replica waiting requests
  serve_router_failover_total{reason}    requests re-routed off a replica (backpressure|death|drain)
  serve_kv_handoff_pages_total           KV pages moved prefill->decode across the pool boundary
  serve_disagg_handoffs_total{outcome}   disaggregated hand-offs by outcome (pages|recompute|failed)
  serve_role_queue_depth{role}           waiting requests per engine-pool role (prefill|decode)
  serve_router_dispatch_seconds          route decision -> replica placement wall time
  fleet_slo_attainment                   finished-weighted fleet SLO attainment roll-up (0..1)
  fleet_pressure_ratio{role}             per-role demand / capacity from the fleet signal bus
  fleet_replica_signal{name,replica}     sampled per-replica fleet-bus signals (queue_depth|tok_per_s)
  fleet_flight_dumps_total{trigger}      correlated fleet flight dumps by latch reason
  fleet_replicas{role}                   live replicas per role in the autoscaled fleet
  fleet_scale_events_total{action,outcome}  autoscale actuations (spawn|retire|rebalance x ok|fault|skipped)
  fleet_autoscale_decision_seconds       signal read -> decision -> actuation wall time
  transport_messages_total{kind,outcome} serving/transport.py messages by kind and terminal outcome
  transport_retries_total{site}          transport retransmissions by send site
  fleet_lease_transitions_total{from,to} serving/membership.py lease transitions (live|suspect|dead)
  serve_handoff_aborts_total{reason}     two-phase KV hand-offs aborted/salvaged by reason
"""
from __future__ import annotations

from . import metrics as _m

# The built-in metric-name catalog: every framework-emitted family, by its
# stable name. The analysis linter (paddle_tpu/analysis, rule TPU301) reads
# this tuple STATICALLY and flags any registry.counter/gauge/histogram call
# in the package whose literal name is absent — adding an instrumented call
# site means adding its family here (and to the docstring table above).
CATALOG = (
    "ops_dispatch_total",
    "jit_compile_total",
    "jit_cache_hits_total",
    "jit_compile_seconds",
    "collective_calls_total",
    "collective_bytes_total",
    "host_collective_rounds_total",
    "host_collective_bytes_total",
    "checkpoint_save_seconds",
    "checkpoint_load_seconds",
    "watchdog_ticks_total",
    "watchdog_fires_total",
    "train_steps_total",
    "dataloader_batches_total",
    "resilience_faults_injected_total",
    "resilience_retries_total",
    "resilience_giveups_total",
    "resilience_ckpt_events_total",
    "resilience_guard_events_total",
    "resilience_preemptions_total",
    "resilience_emergency_save_seconds",
    "checkpoint_async_queue_depth",
    "checkpoint_async_join_seconds",
    "serve_queue_depth",
    "serve_running_seqs",
    "serve_admitted_total",
    "serve_finished_total",
    "serve_preempted_total",
    "serve_steps_total",
    "serve_tokens_total",
    "serve_kv_pool_utilization",
    "serve_prefix_cache_queries_total",
    "serve_prefix_cache_hits_total",
    "serve_ttft_seconds",
    "serve_token_seconds",
    "serve_spec_proposed_tokens_total",
    "serve_spec_accepted_tokens_total",
    "serve_spec_accept_rate",
    "serve_spec_rollback_pages_total",
    "serve_slo_violations_total",
    "serve_slo_attainment",
    "serve_goodput_tokens_total",
    "serve_flight_dumps_total",
    "serve_ttft_quantile_seconds",
    "serve_tpot_quantile_seconds",
    "serve_e2e_quantile_seconds",
    "aot_cache_hits_total",
    "aot_cache_misses_total",
    "aot_cache_load_seconds",
    "aot_cache_export_seconds",
    "aot_cache_fallbacks_total",
    "perf_evidence_rows_total",
    "perf_resolver_decisions_total",
    "perf_step_fraction",
    "perf_program_roofline_ratio",
    "mem_bytes_in_use",
    "mem_peak_bytes",
    "mem_watermark_fraction",
    "mem_pressure_dumps_total",
    "serve_kv_pool_bytes",
    "serve_step_faults_total",
    "serve_request_retries_total",
    "serve_shed_total",
    "serve_drain_seconds",
    "serve_engine_restarts_total",
    "serve_router_routed_total",
    "serve_router_affinity_hits_total",
    "serve_router_replica_queue_depth",
    "serve_router_failover_total",
    "serve_kv_handoff_pages_total",
    "serve_disagg_handoffs_total",
    "serve_role_queue_depth",
    "serve_router_dispatch_seconds",
    "fleet_slo_attainment",
    "fleet_pressure_ratio",
    "fleet_replica_signal",
    "fleet_flight_dumps_total",
    "fleet_replicas",
    "fleet_scale_events_total",
    "fleet_autoscale_decision_seconds",
    "transport_messages_total",
    "transport_retries_total",
    "fleet_lease_transitions_total",
    "serve_handoff_aborts_total",
)

_enabled = _m._ENABLED  # bind the cell once: hot-path guard is _enabled[0]

_TIME_BUCKETS = (0.001, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 30.0, 60.0,
                 300.0, 1800.0)


def _reg() -> "_m.MetricsRegistry":
    return _m.get_registry()


def enabled() -> bool:
    return _enabled[0]


def record_op_dispatch(op: str) -> None:
    if not _enabled[0]:
        return
    _reg().counter("ops_dispatch_total",
                   "eager/traced op dispatches by op name",
                   labelnames=("op",)).labels(op=op).inc()


def record_jit_compile(fn: str, seconds: float) -> None:
    if not _enabled[0]:
        return
    r = _reg()
    r.counter("jit_compile_total", "to_static fresh traces (cache misses)",
              labelnames=("fn",)).labels(fn=fn).inc()
    r.histogram("jit_compile_seconds",
                "wall seconds of to_static calls that traced "
                "(trace+compile+first run)", buckets=_TIME_BUCKETS
                ).observe(seconds)


def record_jit_cache_hit(fn: str) -> None:
    if not _enabled[0]:
        return
    _reg().counter("jit_cache_hits_total",
                   "to_static calls served from the compile cache",
                   labelnames=("fn",)).labels(fn=fn).inc()


def record_collective(op: str, nbytes: int, tier: str) -> None:
    if not _enabled[0]:
        return
    r = _reg()
    lbl = {"op": op, "tier": tier}
    r.counter("collective_calls_total", "collective API calls",
              labelnames=("op", "tier")).labels(**lbl).inc()
    r.counter("collective_bytes_total", "collective payload bytes",
              labelnames=("op", "tier")).labels(**lbl).inc(max(int(nbytes), 0))


def record_host_collective(op: str, nbytes: int) -> None:
    if not _enabled[0]:
        return
    r = _reg()
    r.counter("host_collective_rounds_total",
              "store-routed host collective rounds",
              labelnames=("op",)).labels(op=op).inc()
    r.counter("host_collective_bytes_total",
              "store-routed host collective payload bytes",
              labelnames=("op",)).labels(op=op).inc(max(int(nbytes), 0))


def record_checkpoint(kind: str, seconds: float) -> None:
    if not _enabled[0]:
        return
    _reg().histogram(f"checkpoint_{kind}_seconds",
                     f"distributed checkpoint {kind} wall seconds",
                     buckets=_TIME_BUCKETS).observe(seconds)


def record_watchdog_tick() -> None:
    if not _enabled[0]:
        return
    _reg().counter("watchdog_ticks_total",
                   "StepWatchdog step completions observed").inc()


def record_watchdog_fire() -> None:
    if not _enabled[0]:
        return
    _reg().counter("watchdog_fires_total",
                   "StepWatchdog hang events fired").inc()


def record_train_step() -> None:
    if not _enabled[0]:
        return
    _reg().counter("train_steps_total", "training steps completed").inc()


def record_dataloader_batch() -> None:
    if not _enabled[0]:
        return
    _reg().counter("dataloader_batches_total",
                   "batches yielded to fit/evaluate loops").inc()


def record_fault_injected(site: str, kind: str) -> None:
    if not _enabled[0]:
        return
    _reg().counter("resilience_faults_injected_total",
                   "chaos faults fired by probe site and kind",
                   labelnames=("site", "kind")).labels(
        site=site, kind=kind).inc()


def record_resilience_retry(site: str) -> None:
    if not _enabled[0]:
        return
    _reg().counter("resilience_retries_total",
                   "RetryPolicy retried attempts by call site",
                   labelnames=("site",)).labels(site=site).inc()


def record_resilience_giveup(site: str) -> None:
    if not _enabled[0]:
        return
    _reg().counter("resilience_giveups_total",
                   "RetryPolicy exhaustions (exception re-raised)",
                   labelnames=("site",)).labels(site=site).inc()


def record_ckpt_event(event: str) -> None:
    if not _enabled[0]:
        return
    _reg().counter("resilience_ckpt_events_total",
                   "checkpoint lifecycle events "
                   "(corrupt_detected|fallback|gc)",
                   labelnames=("event",)).labels(event=event).inc()


def record_guard_event(kind: str, action: str) -> None:
    if not _enabled[0]:
        return
    _reg().counter("resilience_guard_events_total",
                   "StepGuard anomalies by kind and action taken",
                   labelnames=("kind", "action")).labels(
        kind=kind, action=action).inc()


def record_preemption(source: str) -> None:
    if not _enabled[0]:
        return
    _reg().counter("resilience_preemptions_total",
                   "preemption notices by source "
                   "(signal|file|env|chaos|peer|api)",
                   labelnames=("source",)).labels(source=source).inc()


def record_emergency_save(seconds: float) -> None:
    if not _enabled[0]:
        return
    _reg().histogram("resilience_emergency_save_seconds",
                     "deadline-driven emergency checkpoint wall seconds",
                     buckets=_TIME_BUCKETS).observe(seconds)


def record_async_queue_depth(depth: int) -> None:
    if not _enabled[0]:
        return
    _reg().gauge("checkpoint_async_queue_depth",
                 "async checkpoint writer threads not yet joined"
                 ).set(float(depth))


def record_async_join(seconds: float) -> None:
    if not _enabled[0]:
        return
    _reg().histogram("checkpoint_async_join_seconds",
                     "wall seconds spent joining async checkpoint "
                     "writers", buckets=_TIME_BUCKETS).observe(seconds)


def record_serve_queue_depth(depth: int) -> None:
    if not _enabled[0]:
        return
    _reg().gauge("serve_queue_depth",
                 "serving requests waiting for admission").set(float(depth))


def record_serve_step(admitted: int, finished: int, preempted: int,
                      queue_depth: int, running: int,
                      pool_utilization: float) -> None:
    """One continuous-batching engine step's worth of scheduler events."""
    if not _enabled[0]:
        return
    r = _reg()
    r.counter("serve_steps_total", "serving engine steps (device calls)") \
        .inc()
    if admitted:
        r.counter("serve_admitted_total",
                  "requests admitted into the continuous batch") \
            .inc(admitted)
    if finished:
        r.counter("serve_finished_total",
                  "requests finished and evicted from the batch") \
            .inc(finished)
    if preempted:
        r.counter("serve_preempted_total",
                  "requests preempted under KV-pool pressure") \
            .inc(preempted)
    r.gauge("serve_queue_depth",
            "serving requests waiting for admission").set(float(queue_depth))
    r.gauge("serve_running_seqs",
            "sequences live in the continuous batch").set(float(running))
    r.gauge("serve_kv_pool_utilization",
            "KV pages held by live sequences / pool size") \
        .set(float(pool_utilization))


def record_serve_prefix(queries: int, hits: int) -> None:
    if not _enabled[0]:
        return
    r = _reg()
    if queries:
        r.counter("serve_prefix_cache_queries_total",
                  "KV prefix-cache lookups at admission").inc(queries)
    if hits:
        r.counter("serve_prefix_cache_hits_total",
                  "prefix-cache lookups reusing >= 1 cached page") \
            .inc(hits)


def record_serve_ttft(seconds: float) -> None:
    if not _enabled[0]:
        return
    _reg().histogram("serve_ttft_seconds",
                     "submit -> first sampled token latency",
                     buckets=_TIME_BUCKETS).observe(seconds)


def record_serve_spec_tokens(proposed: int, accepted: int) -> None:
    """One verify step's speculative outcome: ``proposed`` draft tokens
    fed, ``accepted`` confirmed by longest-prefix greedy verification."""
    if not _enabled[0]:
        return
    r = _reg()
    if proposed:
        r.counter("serve_spec_proposed_tokens_total",
                  "draft tokens fed to speculative verify steps") \
            .inc(proposed)
        r.gauge("serve_spec_accept_rate",
                "accepted/proposed draft ratio of the last verify step") \
            .set(accepted / proposed)
    if accepted:
        r.counter("serve_spec_accepted_tokens_total",
                  "draft tokens confirmed by greedy verification") \
            .inc(accepted)


def record_serve_spec_rollback(pages: int) -> None:
    if not _enabled[0] or not pages:
        return
    _reg().counter("serve_spec_rollback_pages_total",
                   "KV pages released rolling back rejected drafts") \
        .inc(pages)


def record_serve_slo_violation(kind: str) -> None:
    """One SLO deadline miss (kind: ttft | tpot)."""
    if not _enabled[0]:
        return
    _reg().counter("serve_slo_violations_total",
                   "serving SLO deadline misses by kind (ttft|tpot)",
                   labelnames=("kind",)).labels(kind=kind).inc()


def record_serve_slo_attainment(fraction: float) -> None:
    if not _enabled[0]:
        return
    _reg().gauge("serve_slo_attainment",
                 "fraction of SLO-tracked finished requests that met "
                 "every deadline").set(float(fraction))


def record_serve_goodput(tokens: int) -> None:
    """Tokens from a finished request that met its SLO deadlines (0 for
    a request that blew one — those tokens are throughput, not goodput)."""
    if not _enabled[0] or not tokens:
        return
    _reg().counter("serve_goodput_tokens_total",
                   "output tokens from requests that met their SLO "
                   "deadlines").inc(tokens)


def record_serve_flight_dump(trigger: str) -> None:
    if not _enabled[0]:
        return
    _reg().counter("serve_flight_dumps_total",
                   "flight-recorder dumps by trigger "
                   "(stall|pool_exhausted|chaos_fault|slo_blow|manual)",
                   labelnames=("trigger",)).labels(trigger=trigger).inc()


def record_serve_quantiles(kind: str, p50: float, p95: float,
                           p99: float) -> None:
    """Streaming latency sketch quantiles (kind: ttft | tpot | e2e) —
    gauges so dashboards read the engine's bounded-sketch estimates
    without scraping histograms."""
    if not _enabled[0]:
        return
    g = _reg().gauge(f"serve_{kind}_quantile_seconds",
                     "bounded-sketch streaming latency quantile by q "
                     "(p50|p95|p99)", labelnames=("q",))
    g.labels(q="p50").set(float(p50))
    g.labels(q="p95").set(float(p95))
    g.labels(q="p99").set(float(p99))


def record_aot_cache_hit(program: str) -> None:
    if not _enabled[0]:
        return
    _reg().counter("aot_cache_hits_total",
                   "AOT program artifacts deserialized (trace skipped)",
                   labelnames=("program",)).labels(program=program).inc()


def record_aot_cache_miss(program: str) -> None:
    if not _enabled[0]:
        return
    _reg().counter("aot_cache_misses_total",
                   "AOT programs traced+exported fresh (published)",
                   labelnames=("program",)).labels(program=program).inc()


def record_aot_load(seconds: float) -> None:
    if not _enabled[0]:
        return
    _reg().histogram("aot_cache_load_seconds",
                     "artifact deserialize + program-ready wall seconds "
                     "on a cache hit", buckets=_TIME_BUCKETS) \
        .observe(seconds)


def record_aot_export(seconds: float) -> None:
    if not _enabled[0]:
        return
    _reg().histogram("aot_cache_export_seconds",
                     "trace + export + publish wall seconds on a cache "
                     "miss", buckets=_TIME_BUCKETS).observe(seconds)


def record_aot_fallback(reason: str) -> None:
    if not _enabled[0]:
        return
    _reg().counter("aot_cache_fallbacks_total",
                   "AOT cache degraded to fresh/uncached compile "
                   "(corrupt|chaos|io|deserialize|export|run)",
                   labelnames=("reason",)).labels(reason=reason).inc()


def record_perf_evidence_rows(source: str, n: int = 1) -> None:
    """n rows ingested into the perf-evidence ledger from one source."""
    if not _enabled[0] or not n:
        return
    _reg().counter("perf_evidence_rows_total",
                   "perf-evidence ledger rows ingested by source "
                   "(probe|bench|bench_serve|bench_session|mfu_lab|"
                   "autotune|aot_stats|runlog|flight)",
                   labelnames=("source",)).labels(source=source).inc(n)


def record_perf_resolver_decision(flag: str, status: str) -> None:
    """One apply_perf_config outcome for one flag (status: applied|
    deferred|env_override|stale|device_mismatch|corrupt)."""
    if not _enabled[0]:
        return
    _reg().counter("perf_resolver_decisions_total",
                   "perf-config resolver decisions by flag and apply "
                   "outcome",
                   labelnames=("flag", "status")).labels(
        flag=flag, status=status).inc()


def record_perf_step_fraction(component: str, fraction: float) -> None:
    """Step-time anatomy: the fraction of the last attributed step spent
    in one component (compute|collective|data|host)."""
    if not _enabled[0]:
        return
    _reg().gauge("perf_step_fraction",
                 "fraction of the last attributed step's wall time by "
                 "component (compute|collective|data|host)",
                 labelnames=("component",)).labels(
        component=component).set(float(fraction))


def record_perf_roofline(program: str, ratio: float) -> None:
    """Roofline position of one program: arithmetic intensity over the
    device's machine balance (>=1 compute-bound, <1 memory-bound)."""
    if not _enabled[0]:
        return
    _reg().gauge("perf_program_roofline_ratio",
                 "program arithmetic intensity / device machine balance "
                 "(>=1: compute-bound)",
                 labelnames=("program",)).labels(
        program=program).set(float(ratio))


def record_mem_bytes_in_use(pool: str, nbytes: int) -> None:
    """Current device bytes attributed to one memwatch pool (params|
    optimizer|kv_pages|workspace|other|total)."""
    if not _enabled[0]:
        return
    _reg().gauge("mem_bytes_in_use",
                 "device bytes currently attributed to a memwatch pool "
                 "(params|optimizer|kv_pages|workspace|other|total)",
                 labelnames=("pool",)).labels(pool=pool).set(float(nbytes))


def record_mem_peak_bytes(pool: str, nbytes: int) -> None:
    if not _enabled[0]:
        return
    _reg().gauge("mem_peak_bytes",
                 "high-watermark device bytes per memwatch pool "
                 "(resettable via reset_watermarks)",
                 labelnames=("pool",)).labels(pool=pool).set(float(nbytes))


def record_mem_watermark_fraction(fraction: float) -> None:
    if not _enabled[0]:
        return
    _reg().gauge("mem_watermark_fraction",
                 "bytes_in_use / bytes_limit of the last memory snapshot "
                 "(near-OOM trigger input, 0..1)").set(float(fraction))


def record_mem_pressure_dump(trigger: str) -> None:
    if not _enabled[0]:
        return
    _reg().counter("mem_pressure_dumps_total",
                   "memwatch ring dumps by trigger (near_oom|manual)",
                   labelnames=("trigger",)).labels(trigger=trigger).inc()


def record_serve_kv_pool_bytes(nbytes: int) -> None:
    """Device bytes held by live sequences' KV pages (used pages x
    per-page bytes across both K and V pools)."""
    if not _enabled[0]:
        return
    _reg().gauge("serve_kv_pool_bytes",
                 "device bytes of KV pages held by live sequences "
                 "(used pages x per-page K+V bytes)").set(float(nbytes))


def record_serve_step_fault(kind: str) -> None:
    """One contained engine-step fault (kind: chaos | nan_logits | the
    escaping exception's class name)."""
    if not _enabled[0]:
        return
    _reg().counter("serve_step_faults_total",
                   "serving engine steps that raised and were contained "
                   "by the resilience plane (by fault kind)",
                   labelnames=("kind",)).labels(kind=kind).inc()


def record_serve_request_retry(reason: str) -> None:
    """One request requeued for prefix recompute after a contained
    fault (reason: step_fault)."""
    if not _enabled[0]:
        return
    _reg().counter("serve_request_retries_total",
                   "serving requests requeued for recompute by reason",
                   labelnames=("reason",)).labels(reason=reason).inc()


def record_serve_shed(policy: str) -> None:
    """One submission refused by admission control under the named
    backpressure policy (block | reject | shed)."""
    if not _enabled[0]:
        return
    _reg().counter("serve_shed_total",
                   "serving submissions refused by admission control "
                   "(by backpressure policy)",
                   labelnames=("policy",)).labels(policy=policy).inc()


def record_serve_drain(seconds: float) -> None:
    if not _enabled[0]:
        return
    _reg().histogram("serve_drain_seconds",
                     "graceful-drain wall seconds (stop admission -> "
                     "manifest exported)", buckets=_TIME_BUCKETS) \
        .observe(seconds)


def record_serve_engine_restart() -> None:
    """One drain manifest replayed into a (re)started engine."""
    if not _enabled[0]:
        return
    _reg().counter("serve_engine_restarts_total",
                   "drain manifests replayed into a fresh serving "
                   "engine after a restart").inc()


def record_router_routed(policy: str, affinity_hit: bool = False) -> None:
    """One replica-router routing decision. ``policy`` names what
    actually decided the placement (affinity | least_loaded | random |
    round_robin); ``affinity_hit`` marks submissions that landed on a
    replica already holding their prefix."""
    if not _enabled[0]:
        return
    r = _reg()
    r.counter("serve_router_routed_total",
              "replica-router routing decisions by deciding policy",
              labelnames=("policy",)).labels(policy=policy).inc()
    if affinity_hit:
        r.counter("serve_router_affinity_hits_total",
                  "submissions routed to a replica already holding "
                  "their prompt prefix").inc()


def record_router_queue_depth(replica: int, depth: int) -> None:
    """One replica's waiting-queue depth (refreshed per router step)."""
    if not _enabled[0]:
        return
    _reg().gauge("serve_router_replica_queue_depth",
                 "waiting requests per router replica",
                 labelnames=("replica",)) \
        .labels(replica=str(replica)).set(float(depth))


def record_router_failover(reason: str) -> None:
    """One request re-routed off its chosen replica (reason:
    backpressure | death | drain)."""
    if not _enabled[0]:
        return
    _reg().counter("serve_router_failover_total",
                   "requests re-routed off a replica by reason",
                   labelnames=("reason",)).labels(reason=reason).inc()


def record_kv_handoff(pages: int) -> None:
    """One prefill->decode KV-page export: ``pages`` physical pages'
    contents moved across the pool boundary (0 for a 1-token prompt)."""
    if not _enabled[0] or not pages:
        return
    _reg().counter("serve_kv_handoff_pages_total",
                   "KV pages moved prefill->decode across the "
                   "disaggregated pool boundary").inc(pages)


def record_disagg_handoff(outcome: str) -> None:
    """One disaggregated hand-off resolved (outcome: pages = KV import
    landed, recompute = fallback to prompt recompute on the decode
    replica, failed = no decode survivor — terminal error)."""
    if not _enabled[0]:
        return
    _reg().counter("serve_disagg_handoffs_total",
                   "prefill->decode hand-offs by outcome "
                   "(pages|recompute|failed)",
                   labelnames=("outcome",)).labels(outcome=outcome).inc()


def record_role_queue_depth(role: str, depth: int) -> None:
    """Aggregate waiting-queue depth of one engine-pool role."""
    if not _enabled[0]:
        return
    _reg().gauge("serve_role_queue_depth",
                 "waiting requests per engine-pool role "
                 "(prefill|decode)",
                 labelnames=("role",)).labels(role=role).set(float(depth))


def record_router_dispatch(seconds: float) -> None:
    """Wall time of one router dispatch: route decision through replica
    placement (including any backpressure fail-over hops)."""
    if not _enabled[0]:
        return
    _reg().histogram("serve_router_dispatch_seconds",
                     "route decision -> replica placement wall time",
                     buckets=_TIME_BUCKETS).observe(seconds)


def record_fleet_slo_attainment(value: float) -> None:
    """The fleet signal bus's finished-weighted SLO attainment roll-up
    (replicas with no tracked finishes carry zero weight)."""
    if not _enabled[0]:
        return
    _reg().gauge("fleet_slo_attainment",
                 "finished-weighted fleet SLO attainment roll-up") \
        .set(float(value))


def record_fleet_pressure(role: str, value: float) -> None:
    """One role pool's pressure (demand / capacity) from the bus."""
    if not _enabled[0]:
        return
    _reg().gauge("fleet_pressure_ratio",
                 "per-role demand / capacity from the fleet signal bus",
                 labelnames=("role",)).labels(role=role).set(float(value))


def record_fleet_replica_signal(name: str, replica: int,
                                value: float) -> None:
    """One sampled per-replica signal from the fleet bus ring."""
    if not _enabled[0]:
        return
    _reg().gauge("fleet_replica_signal",
                 "sampled per-replica fleet-bus signals",
                 labelnames=("name", "replica")) \
        .labels(name=name, replica=str(replica)).set(float(value))


def record_fleet_flight_dump(trigger: str) -> None:
    """One correlated fleet flight dump latched (by reason)."""
    if not _enabled[0]:
        return
    _reg().counter("fleet_flight_dumps_total",
                   "correlated fleet flight dumps by latch reason",
                   labelnames=("trigger",)).labels(trigger=trigger).inc()


def record_fleet_scale_replicas(role: str, n: int) -> None:
    """Live replica count for one role pool of the autoscaled fleet
    (role "unified" for role-less fleets)."""
    if not _enabled[0]:
        return
    _reg().gauge("fleet_replicas",
                 "live replicas per role in the autoscaled fleet",
                 labelnames=("role",)).labels(role=role).set(float(n))


def record_fleet_scale_event(action: str, outcome: str) -> None:
    """One autoscale actuation: action spawn|retire|rebalance, outcome
    ok|fault|skipped."""
    if not _enabled[0]:
        return
    _reg().counter("fleet_scale_events_total",
                   "autoscale actuations by action and outcome",
                   labelnames=("action", "outcome")) \
        .labels(action=action, outcome=outcome).inc()


def record_fleet_scale_decision(seconds: float) -> None:
    """Wall time of one autoscaler control pass: signal read through
    decision and (possibly chaos-probed) actuation."""
    if not _enabled[0]:
        return
    _reg().histogram("fleet_autoscale_decision_seconds",
                     "signal read -> decision -> actuation wall time",
                     buckets=_TIME_BUCKETS).observe(seconds)


def record_transport_message(kind: str, outcome: str) -> None:
    """One transport message reaching a terminal outcome (delivered |
    dropped | deduped | partitioned | torn | expired | unroutable)."""
    if not _enabled[0]:
        return
    _reg().counter("transport_messages_total",
                   "replica-transport messages by kind and terminal "
                   "outcome",
                   labelnames=("kind", "outcome")) \
        .labels(kind=kind, outcome=outcome).inc()


def record_transport_retry(site: str) -> None:
    """One transport retransmission of an unacked message (site names
    the sending channel, e.g. transport.kv_prepare)."""
    if not _enabled[0]:
        return
    _reg().counter("transport_retries_total",
                   "transport retransmissions by send site",
                   labelnames=("site",)).labels(site=site).inc()


def record_lease_transition(frm: str, to: str) -> None:
    """One membership lease transition (live|suspect|dead)."""
    if not _enabled[0]:
        return
    _reg().counter("fleet_lease_transitions_total",
                   "membership lease state transitions",
                   labelnames=("from", "to")) \
        .labels(**{"from": frm, "to": to}).inc()


def record_handoff_abort(reason: str) -> None:
    """One two-phase KV hand-off aborted (reason: the importer's nack
    cause, ack_timeout for a retry give-up, ack_lost for a hand-off
    that committed without its ack ever arriving)."""
    if not _enabled[0]:
        return
    _reg().counter("serve_handoff_aborts_total",
                   "two-phase KV hand-offs aborted by reason",
                   labelnames=("reason",)).labels(reason=reason).inc()


def record_serve_tokens(n: int, step_seconds: float) -> None:
    """n tokens sampled by one step of step_seconds wall time."""
    if not _enabled[0]:
        return
    r = _reg()
    if n:
        r.counter("serve_tokens_total",
                  "tokens sampled across all serving requests").inc(n)
    h = r.histogram("serve_token_seconds",
                    "per-token latency (wall time of the step that "
                    "produced it)", buckets=_TIME_BUCKETS)
    for _ in range(n):
        h.observe(step_seconds)
