"""Metrics plane: thread-safe Counter / Gauge / Histogram registry.

Reference parity: the C++ profiler's summary statistics plus the
production-monitoring role the reference fills with external exporters.
TPU-native design: one in-process registry the whole framework reports
into — op dispatch, jit compiles, collectives, checkpoints, watchdog —
exportable as a plain dict, JSON, or Prometheus text exposition format.

Recording is OFF by default and gated on one module-level boolean
(``_ENABLED[0]``), so instrumented hot paths (eager op dispatch) pay a
single list-index + bool check when disabled. ``enable_metrics()`` turns
the plane on; the registry itself always works (tests and user code may
record into a private registry regardless of the global switch).
"""
from __future__ import annotations

import json
import math
import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "get_registry", "reset_registry",
    "enable_metrics", "disable_metrics", "metrics_enabled",
    "QUANTILE_RELATIVE_ERROR",
]

# the one hot-path guard: instrumented call sites check _ENABLED[0] before
# touching the registry (a list so other modules can bind the cell once)
_ENABLED: List[bool] = [False]


def enable_metrics(flag: bool = True) -> None:
    """Turn the global metrics plane on/off (off by default)."""
    _ENABLED[0] = bool(flag)


def disable_metrics() -> None:
    enable_metrics(False)


def metrics_enabled() -> bool:
    return _ENABLED[0]


def _check_labels(labelnames: Sequence[str], labels: Dict[str, str]) -> Tuple:
    if set(labels) != set(labelnames):
        raise ValueError(
            f"labels {sorted(labels)} do not match declared labelnames "
            f"{sorted(labelnames)}")
    return tuple(str(labels[n]) for n in labelnames)


class _Metric:
    """Base: a named family with optional labels; children keyed by the
    tuple of label values (in declared labelname order)."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: Dict[Tuple, "_Metric"] = {}

    def labels(self, **labels) -> "_Metric":
        """The child series for these label values (created on first use)."""
        if not self.labelnames:
            raise ValueError(f"metric {self.name!r} declares no labels")
        key = _check_labels(self.labelnames, labels)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = type(self)(self.name, self.help)
                child._lock = self._lock  # one lock per family
                self._children[key] = child
            return child

    def _series(self) -> Iterable[Tuple[Tuple, "_Metric"]]:
        if self.labelnames:
            with self._lock:
                return list(self._children.items())
        return [((), self)]

    def _require_no_labels(self) -> None:
        """Recording on a labeled FAMILY would accumulate into a value no
        exporter emits — force the caller through .labels(...)."""
        if self.labelnames:
            raise ValueError(
                f"metric {self.name!r} declares labels {self.labelnames}; "
                "record through .labels(...)")

    def _label_str(self, key: Tuple, extra: str = "") -> str:
        parts = [f'{n}="{v}"' for n, v in zip(self.labelnames, key)]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""


class Counter(_Metric):
    """Monotonically increasing count."""

    kind = "counter"

    def __init__(self, name, help="", labelnames=()):
        super().__init__(name, help, labelnames)
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("Counter can only increase")
        self._require_no_labels()
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def snapshot(self):
        if self.labelnames:
            return {key: c._value for key, c in self._series()}
        return self._value


class Gauge(_Metric):
    """A value that can go up and down."""

    kind = "gauge"

    def __init__(self, name, help="", labelnames=()):
        super().__init__(name, help, labelnames)
        self._value = 0.0

    def set(self, value: float) -> None:
        self._require_no_labels()
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        self._require_no_labels()
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def snapshot(self):
        if self.labelnames:
            return {key: c._value for key, c in self._series()}
        return self._value


_DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0,
                    30.0, 60.0, 300.0)

# Bounded streaming-quantile sketch grid (``track_quantiles=True``): a
# geometric bucket ladder from _Q_MIN with per-bucket growth _Q_GROWTH.
# quantile(q) returns the UPPER edge of the bucket holding the q-th
# order statistic, so the estimate e of a true value v in range obeys
# v <= e <= v * _Q_GROWTH — a fixed 5% relative error bound from a
# fixed-size int array (no unbounded observation list on the hot path).
_Q_MIN = 1e-6
_Q_GROWTH = 1.05
_Q_BUCKETS = 512          # reaches _Q_MIN * 1.05**511 ~ 6.7e4 (~18.6 h)
_Q_LOG_G = math.log(_Q_GROWTH)

# the public error-bound contract consumers assert against (e.g. the
# serving bench cross-checks engine sketch quantiles vs its own exact
# offline order statistics within this factor)
QUANTILE_RELATIVE_ERROR = _Q_GROWTH


class Histogram(_Metric):
    """Cumulative-bucket histogram (Prometheus semantics: each bucket counts
    observations <= its upper bound; +Inf is implicit = count).

    ``track_quantiles=True`` additionally maintains a bounded log-spaced
    sketch (fixed ``_Q_BUCKETS`` int array) so ``quantile(q)`` answers
    streaming p50/p95/p99 within ``QUANTILE_RELATIVE_ERROR`` relative
    error — memory stays O(1) however many values are observed."""

    kind = "histogram"

    def __init__(self, name, help="", labelnames=(),
                 buckets: Sequence[float] = _DEFAULT_BUCKETS,
                 track_quantiles: bool = False):
        super().__init__(name, help, labelnames)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self.track_quantiles = bool(track_quantiles)
        self._counts = [0] * len(self.buckets)
        self._qcounts = [0] * _Q_BUCKETS if self.track_quantiles else None
        self._count = 0
        self._sum = 0.0

    def labels(self, **labels) -> "Histogram":
        if not self.labelnames:
            raise ValueError(f"metric {self.name!r} declares no labels")
        key = _check_labels(self.labelnames, labels)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = Histogram(self.name, self.help, buckets=self.buckets,
                                  track_quantiles=self.track_quantiles)
                child._lock = self._lock
                self._children[key] = child
            return child

    @staticmethod
    def _q_index(value: float) -> int:
        if value <= _Q_MIN:
            return 0
        return min(_Q_BUCKETS - 1,
                   1 + int(math.log(value / _Q_MIN) / _Q_LOG_G))

    def observe(self, value: float) -> None:
        self._require_no_labels()
        value = float(value)
        with self._lock:
            self._count += 1
            self._sum += value
            for i, b in enumerate(self.buckets):
                if value <= b:
                    self._counts[i] += 1
            if self._qcounts is not None:
                self._qcounts[self._q_index(value)] += 1

    def quantile(self, q: float) -> float:
        """Sketch estimate of the q-th quantile (the ceil(q*n)-th order
        statistic's bucket upper edge). 0.0 with no observations."""
        if self._qcounts is None:
            raise ValueError(
                f"histogram {self.name!r} was not created with "
                "track_quantiles=True")
        if not 0.0 < q <= 1.0:
            raise ValueError(f"quantile wants 0 < q <= 1, got {q}")
        with self._lock:
            total = self._count
            if total == 0:
                return 0.0
            rank = max(1, math.ceil(q * total))
            seen = 0
            for i, n in enumerate(self._qcounts):
                seen += n
                if seen >= rank:
                    return _Q_MIN * (_Q_GROWTH ** i)
        return _Q_MIN * (_Q_GROWTH ** (_Q_BUCKETS - 1))

    @property
    def mean(self) -> float:
        with self._lock:
            return self._sum / self._count if self._count else 0.0

    def time(self):
        """Context manager observing the elapsed wall seconds."""
        hist = self

        class _Timer:
            def __enter__(self):
                self._t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                hist.observe(time.perf_counter() - self._t0)
                return False

        return _Timer()

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def snapshot(self):
        def one(h):
            out = {"count": h._count, "sum": h._sum,
                   "buckets": dict(zip(h.buckets, h._counts))}
            if h._qcounts is not None and h._count:
                out["quantiles"] = {q: h.quantile(q)
                                    for q in (0.5, 0.95, 0.99)}
            return out
        if self.labelnames:
            return {key: one(h) for key, h in self._series()}
        return one(self)


class MetricsRegistry:
    """A named collection of metric families. ``counter``/``gauge``/
    ``histogram`` are get-or-create (idempotent re-registration with the
    same kind); ``snapshot`` returns plain dicts suitable for JSON."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name, help, labelnames, **kwargs):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as {m.kind}")
                if m.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} already registered with labels "
                        f"{m.labelnames}, got {tuple(labelnames)}")
                return m
            m = cls(name, help, labelnames=labelnames, **kwargs)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = _DEFAULT_BUCKETS,
                  track_quantiles: bool = False) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets,
                                   track_quantiles=track_quantiles)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def unregister(self, name: str) -> None:
        with self._lock:
            self._metrics.pop(name, None)

    def clear(self) -> None:
        with self._lock:
            self._metrics.clear()

    # -- export ----------------------------------------------------------------
    def snapshot(self) -> Dict[str, object]:
        """{name: value | {label-tuple: value} | histogram dict}."""
        with self._lock:
            metrics = list(self._metrics.values())
        out = {}
        for m in metrics:
            if m.labelnames:
                out[m.name] = {",".join(f"{n}={v}" for n, v in
                                        zip(m.labelnames, key)): val
                               for key, val in m.snapshot().items()}
            else:
                out[m.name] = m.snapshot()
        return out

    def to_json(self, indent: Optional[int] = None) -> str:
        return json.dumps(self.snapshot(), indent=indent, sort_keys=True)

    def to_prometheus_text(self) -> str:
        """Prometheus text exposition format (v0.0.4)."""
        with self._lock:
            metrics = list(self._metrics.values())
        lines: List[str] = []
        for m in metrics:
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for key, series in m._series():
                if isinstance(series, Histogram):
                    cum = 0
                    for b, c in zip(series.buckets, series._counts):
                        cum = c  # counts are already cumulative per bucket
                        lbl = m._label_str(key, f'le="{b}"')
                        lines.append(f"{m.name}_bucket{lbl} {cum}")
                    lbl = m._label_str(key, 'le="+Inf"')
                    lines.append(f"{m.name}_bucket{lbl} {series._count}")
                    lines.append(
                        f"{m.name}_sum{m._label_str(key)} {series._sum}")
                    lines.append(
                        f"{m.name}_count{m._label_str(key)} {series._count}")
                else:
                    lines.append(
                        f"{m.name}{m._label_str(key)} {series._value}")
        return "\n".join(lines) + ("\n" if lines else "")


_default: List[Optional[MetricsRegistry]] = [None]
_default_lock = threading.Lock()


def get_registry() -> MetricsRegistry:
    """The process-wide default registry (created on first use)."""
    if _default[0] is None:
        with _default_lock:
            if _default[0] is None:
                _default[0] = MetricsRegistry()
    return _default[0]


def reset_registry() -> None:
    """Drop every metric in the default registry (tests)."""
    if _default[0] is not None:
        _default[0].clear()
