"""Structured per-rank run log: JSONL step records for trajectory capture.

Each rank writes one ``.jsonl`` file: a ``meta`` header line followed by
one ``step`` record per training step. The schema is stable (bench.py and
BENCH_* trajectory tooling parse it):

  {"kind": "meta", "rank": 0, "world": 1, "unix_time": ...,
   "flops_per_step": ..., "peak_flops": ..., ...user meta}
  {"kind": "step", "step": 0, "step_time_ms": 12.3, "loss": 2.71,
   "tokens": 8192, "tokens_per_s": 665k, "mfu": 0.41, "unix_time": ...}

``mfu`` is a FLOPs-based model-flops-utilization estimate:
``flops_per_step / step_time_s / peak_flops`` — ``flops_per_step`` comes
from :func:`model_flops_per_step` (a jaxpr walk via hapi.dynamic_flops,
x3 for forward+backward) and ``peak_flops`` from the constructor or the
``PADDLE_TPU_PEAK_FLOPS`` env var. Missing either leaves ``mfu: null``
rather than inventing a number.
"""
from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional

__all__ = ["RunLog", "read_runlog", "model_flops_per_step"]


def model_flops_per_step(net, input_size, dtypes=None) -> int:
    """FLOPs of one training step of ``net`` at ``input_size``: the traced
    forward cost x3 (backward ~= 2x forward, the standard estimate)."""
    from ..hapi.dynamic_flops import flops
    return 3 * int(flops(net, input_size, dtypes=dtypes))


class RunLog:
    """Append-only JSONL step log for one rank.

    path: file or directory (directory => ``<path>/runlog_rank<r>.jsonl``).
    """

    def __init__(self, path: str, rank: Optional[int] = None,
                 world: Optional[int] = None,
                 flops_per_step: Optional[float] = None,
                 peak_flops: Optional[float] = None,
                 meta: Optional[Dict] = None):
        if rank is None or world is None:
            from ..distributed.host_collectives import world_info
            r, w = world_info()
            rank = r if rank is None else rank
            world = w if world is None else world
        self.rank = int(rank)
        self.world = int(world)
        if os.path.isdir(path) or path.endswith(os.sep):
            os.makedirs(path, exist_ok=True)
            path = os.path.join(path, f"runlog_rank{self.rank}.jsonl")
        else:
            parent = os.path.dirname(path)
            if parent:
                os.makedirs(parent, exist_ok=True)
        self.path = path
        self.flops_per_step = flops_per_step
        if peak_flops is None:
            env = os.environ.get("PADDLE_TPU_PEAK_FLOPS")
            peak_flops = float(env) if env else None
        self.peak_flops = peak_flops
        self._f = open(path, "w")
        self._step = 0
        self._last_t: Optional[float] = None
        header = {"kind": "meta", "rank": self.rank, "world": self.world,
                  "unix_time": time.time(),
                  "flops_per_step": flops_per_step,
                  "peak_flops": peak_flops}
        header.update(meta or {})
        self._write(header)
        # live perf-evidence stream: when PADDLE_PERF_EVIDENCE names a
        # ledger (tools/supervise.py threads one per generation), every
        # step record is appended as a normalized evidence row so the
        # crash report / resolver read measurements without re-parsing
        # rank logs. Best-effort: evidence must never break training.
        self._evidence = None
        self._device_kind = (meta or {}).get("device_kind") or \
            (meta or {}).get("device")
        ev_path = os.environ.get("PADDLE_PERF_EVIDENCE", "").strip()
        if ev_path:
            try:
                from . import evidence as _ev
                self._evidence = _ev.Ledger(ev_path)
                self._evidence.append_line(_ev.make_row(
                    "runlog", "runlog_meta",
                    {"rank": self.rank, "world": self.world,
                     "flops_per_step": flops_per_step,
                     "peak_flops": peak_flops},
                    file=os.path.basename(self.path),
                    device_kind=self._device_kind))
            except Exception:  # noqa: BLE001 — advisory stream only
                self._evidence = None

    def _write(self, rec: Dict) -> None:
        self._f.write(json.dumps(rec) + "\n")
        self._f.flush()

    def _mfu(self, step_time_ms: float) -> Optional[float]:
        if not self.flops_per_step or not self.peak_flops or \
                step_time_ms <= 0:
            return None
        achieved = self.flops_per_step / (step_time_ms / 1000.0)
        return achieved / self.peak_flops

    def log_step(self, step: Optional[int] = None,
                 step_time_ms: Optional[float] = None,
                 loss: Optional[float] = None,
                 tokens: Optional[int] = None, **extra) -> Dict:
        """Record one step. With ``step_time_ms=None`` the wall time since
        the previous ``log_step`` (or ``mark``) is used."""
        now = time.perf_counter()
        if step_time_ms is None and self._last_t is not None:
            step_time_ms = (now - self._last_t) * 1000.0
        self._last_t = now
        if step is None:
            step = self._step
        self._step = step + 1
        tokens_per_s = None
        if tokens is not None and step_time_ms:
            tokens_per_s = tokens / (step_time_ms / 1000.0)
        rec = {"kind": "step", "step": int(step),
               "step_time_ms": step_time_ms,
               "loss": None if loss is None else float(loss),
               "tokens": tokens, "tokens_per_s": tokens_per_s,
               "mfu": None if step_time_ms is None
               else self._mfu(step_time_ms),
               "unix_time": time.time()}
        rec.update(extra)
        self._write(rec)
        if self._evidence is not None:
            try:
                from . import evidence as _ev
                self._evidence.append_line(_ev.make_row(
                    "runlog", "train_step",
                    {k: rec.get(k) for k in
                     ("step", "step_time_ms", "loss", "tokens",
                      "tokens_per_s", "mfu")},
                    file=os.path.basename(self.path),
                    device_kind=self._device_kind))
            except Exception:  # noqa: BLE001 — advisory stream only
                self._evidence = None
        return rec

    def mark(self) -> None:
        """Start the wall-clock for the next ``log_step`` (call right
        before the first step so step 0 gets a time)."""
        self._last_t = time.perf_counter()

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def read_runlog(path: str) -> List[Dict]:
    """Parse a runlog JSONL file back into a list of record dicts."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out
