"""Memory observability plane: device-memory ledger + near-OOM flight trigger.

The perf-evidence plane (PR 10) answers "where did the time go"; this
module answers **"where did the HBM go"** — the binding constraint behind
every memory-shaped failure the stack can hit: a remat/batch rung that
OOMs mid-campaign, a KV pool sized one page too greedy, a ZeRO layout
whose optimizer state quietly replicated. Three layers share one
``MemoryWatcher`` object wired through the SpmdTrainer and ServingEngine
seams:

  * **Device-memory ledger** — per-step snapshots of the accelerator's
    allocator counters (``bytes_in_use`` / ``peak_bytes_in_use`` /
    ``bytes_limit`` from PJRT ``Device.memory_stats()``, read through
    ``paddle_tpu.device``), with a CPU fallback that sums
    ``jax.live_arrays()`` by shape×dtype when the backend reports no
    counters. Each snapshot is attributed into **named pools** via
    lightweight array tagging: integration seams register a pool name
    with a zero-arg provider returning the live pytree (params,
    optimizer state, KV pages), the watcher sums leaf ``nbytes`` per
    pool, and whatever the pools cannot explain lands in ``other``
    (workspace, XLA temp buffers, untagged arrays). Snapshots live in a
    bounded ring (``deque(maxlen)``) with per-pool high watermarks.

  * **Near-OOM flight trigger** — when ``bytes_in_use / bytes_limit``
    crosses the configured high-watermark fraction, the ring dumps to
    JSON through the same machinery as the PR 9 serving flight recorder:
    latched once per reason (one pressure event = one postmortem, not a
    dump storm), the dump names the pool whose **growth since the first
    snapshot** is largest (what *filled* the chip, not what merely sat
    on it), and the whole snapshot+dump path can NEVER raise into the
    driver — ``mem.snapshot`` is a chaos site drilling exactly that
    (``tools/chaos_drill.py --mem``).

  * **Watermark accounting** — per-pool and overall peaks, resettable
    (``reset_watermarks()`` also resets the device-level peak counters
    via ``device.reset_peak_memory_stats()``) so per-phase peaks — warm
    start vs steady state, prefill vs decode — are measurable.

Gate discipline (same as PR 1/PR 9): the plane is DISARMED by default —
integrations hold ``memwatch=None`` and every instrumented seam costs
one ``is None`` check (microbench-pinned). Arm per object with
``SpmdTrainer(memwatch=True | MemWatchConfig(...))`` /
``EngineConfig(memwatch=...)`` or globally with ``PADDLE_MEMWATCH=1``;
``PADDLE_MEMWATCH_DUMP=<file>`` names the pressure-dump file (also arms
— ``tools/supervise.py`` threads a per-generation path and inlines the
dump into crash reports) and ``PADDLE_MEMWATCH_WATERMARK`` overrides the
trigger fraction. jax is imported lazily inside snapshot paths so the
module stays importable through the jax-free tools bootstrap.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from ..resilience import chaos
from . import instrument as _instr

logger = logging.getLogger(__name__)

ENV_MEMWATCH = "PADDLE_MEMWATCH"
ENV_DUMP = "PADDLE_MEMWATCH_DUMP"
ENV_WATERMARK = "PADDLE_MEMWATCH_WATERMARK"

#: canonical pool names the integrations register (metric label values);
#: ``other`` is computed, never registered: bytes_in_use minus the tagged
#: pools — workspace, XLA temps, and anything nobody claimed.
POOLS = ("params", "optimizer", "kv_pages", "workspace")

_TRUTHY = ("1", "true", "on", "yes")


def tree_bytes(tree) -> int:
    """Sum of per-leaf device bytes over a pytree of arrays. Works on
    jax arrays, numpy arrays and ShapeDtypeStructs (``nbytes`` first,
    shape×itemsize fallback); non-array leaves count 0."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        n = getattr(leaf, "nbytes", None)
        if isinstance(n, (int, float)):
            total += int(n)
            continue
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        size = 1
        for d in shape:
            size *= int(d)
        total += size * int(getattr(dtype, "itemsize", None)
                            or _dtype_itemsize(dtype))
    return total


def _dtype_itemsize(dtype) -> int:
    import numpy as np
    try:
        return int(np.dtype(dtype).itemsize)
    except TypeError:
        return 0


def _atomic_json(path: str, payload, indent: Optional[int] = None) -> None:
    """tmp-write + rename so readers (supervise, serve_top) never see a
    torn dump; the orphaned tmp is removed if the dump itself fails."""
    tmp = f"{path}.tmp-{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=indent)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


class MemWatchConfig:
    """Knobs for one memory watcher.

    ring_steps bounds the snapshot ring; watermark is the near-OOM
    trigger fraction of ``bytes_limit`` (default 0.92, or the
    ``PADDLE_MEMWATCH_WATERMARK`` env); dump_path defaults to the
    ``PADDLE_MEMWATCH_DUMP`` env; limit_bytes overrides the device's
    reported ``bytes_limit`` — the ONLY way to exercise the pressure
    trigger on a backend (CPU) that reports no limit, and a way to
    enforce a tighter budget than the physical HBM on real silicon;
    stats_fn replaces the device-counter read entirely (a zero-arg
    callable returning the stats dict) — the deterministic-pressure
    hook ``tools/chaos_drill.py --mem`` and the tests drive, immune to
    whatever else the process has live."""

    def __init__(self, ring_steps: int = 256,
                 watermark: Optional[float] = None,
                 dump_path: Optional[str] = None,
                 limit_bytes: Optional[int] = None,
                 device: int = 0,
                 stats_fn: Optional[Callable[[], Dict[str, Any]]] = None):
        if ring_steps < 1:
            raise ValueError(f"ring_steps must be >= 1, got {ring_steps}")
        if watermark is None:
            env = os.environ.get(ENV_WATERMARK, "").strip()
            try:
                watermark = float(env) if env else 0.92
            except ValueError:
                watermark = 0.92
        if not 0.0 < watermark <= 1.0:
            raise ValueError(
                f"watermark must be a fraction in (0, 1], got {watermark}")
        self.ring_steps = int(ring_steps)
        self.watermark = float(watermark)
        self.dump_path = dump_path
        self.limit_bytes = int(limit_bytes) if limit_bytes else None
        self.device = int(device)
        self.stats_fn = stats_fn


class MemoryWatcher:
    """The armed memory-observability plane for one trainer or engine.

    Snapshot hooks are called by the integration under its own lock
    (trainer step / engine step); the watcher's RLock additionally
    protects concurrent ``telemetry()`` / ``dump()`` readers on other
    threads. Lock order is always integration -> watcher, never the
    reverse."""

    def __init__(self, config: Optional[MemWatchConfig] = None):
        cfg = config or MemWatchConfig()
        self.config = cfg
        self.armed = True
        self._lock = threading.RLock()
        # one (monotonic, wall) instant pair: every exported timestamp
        # derives from it, so the chaos-probed snapshot/dump path never
        # reads a jumpable clock (TPU201 discipline, same as serving/obs)
        self._anchor_mono = time.monotonic()
        self._anchor_wall = time.time()
        self._ring: "deque[dict]" = deque(maxlen=cfg.ring_steps)
        self._pools: Dict[str, Callable[[], Any]] = {}
        self._baseline: Optional[Dict[str, int]] = None  # first snapshot
        self.watermarks: Dict[str, Any] = {
            "peak_bytes_in_use": 0, "peak_fraction": 0.0, "pools": {}}
        self.snapshots = 0
        self.snapshot_failures = 0
        self._latched: set = set()
        self.dumps: List[Dict[str, Any]] = []
        self.dump_failures = 0
        self.dump_path = cfg.dump_path if cfg.dump_path is not None \
            else (os.environ.get(ENV_DUMP, "").strip() or None)
        self._identity: Optional[tuple] = None

    # -- clock ----------------------------------------------------------------
    def _wall(self, mono: float) -> float:
        return self._anchor_wall + (mono - self._anchor_mono)

    # -- pool tagging ---------------------------------------------------------
    def register_pool(self, name: str,
                      provider: Callable[[], Any]) -> None:
        """Tag a named pool: ``provider`` is a zero-arg callable returning
        the pool's CURRENT pytree of arrays (called at every snapshot, so
        a trainer whose params are fresh arrays each step stays
        attributed without the watcher holding stale references)."""
        if not callable(provider):
            raise TypeError(f"pool {name!r} provider must be callable")
        with self._lock:
            self._pools[str(name)] = provider

    def _pool_bytes(self) -> Dict[str, int]:
        out = {}
        for name in sorted(self._pools):
            try:
                out[name] = tree_bytes(self._pools[name]())
            except Exception:  # noqa: BLE001 — attribution must not raise
                out[name] = 0
        return out

    # -- the ledger -----------------------------------------------------------
    def snapshot(self, step: Optional[int] = None) -> Optional[dict]:
        """Take one device-memory snapshot into the ring; returns the
        record, or None on failure. NEVER raises — this runs on the
        trainer/engine driver path, and a memory probe that kills the
        step it was watching is worse than no probe (the ``mem.snapshot``
        chaos site drills exactly that)."""
        if not self.armed:
            return None
        try:
            chaos.site("mem.snapshot")
            return self._snapshot_inner(step)
        except Exception:  # noqa: BLE001 — ledger-on-pressure must not raise
            with self._lock:
                self.snapshot_failures += 1
            logger.warning("memwatch: snapshot failed", exc_info=True)
            return None

    def _device_stats(self) -> Dict[str, Any]:
        """Allocator counters with the CPU fallback: a backend that
        reports no ``bytes_in_use`` (CPU PJRT returns None) is summed
        from ``jax.live_arrays()`` by shape×dtype instead."""
        if self.config.stats_fn is not None:
            stats = dict(self.config.stats_fn())
            stats.setdefault("bytes_in_use", 0)
            stats.setdefault("source", "injected")
            stats.setdefault("peak_bytes_in_use", stats.get("bytes_in_use",
                                                            0))
            stats.setdefault("bytes_limit", None)
            return stats
        from .. import device as _device
        stats = _device.memory_stats(self.config.device)
        if stats.get("bytes_in_use"):
            return {
                "bytes_in_use": int(stats["bytes_in_use"]),
                "peak_bytes_in_use":
                    _device.max_memory_allocated(self.config.device),
                "bytes_limit": int(stats.get("bytes_limit") or 0) or None,
                "source": "pjrt",
            }
        live = _device.live_array_bytes()
        _device._note_peak(self.config.device, live)
        return {
            "bytes_in_use": live,
            "peak_bytes_in_use":
                _device.max_memory_allocated(self.config.device) or live,
            "bytes_limit": None,
            "source": "live_arrays",
        }

    def _snapshot_inner(self, step: Optional[int]) -> dict:
        mono = time.monotonic()
        stats = self._device_stats()
        pools = self._pool_bytes()
        tagged = sum(pools.values())
        # tagged pools are a LOWER BOUND on true usage: on a PJRT
        # backend bytes_in_use already covers them, but the CPU
        # live-arrays fallback cannot see host-side pool storage (numpy
        # pages), so the ledger takes the max rather than undercounting
        in_use = max(stats["bytes_in_use"], tagged)
        limit = self.config.limit_bytes or stats["bytes_limit"]
        fraction = (in_use / limit) if limit else None
        rec = {
            "step": step,
            "t_mono_s": round(mono, 6),
            "bytes_in_use": in_use,
            "peak_bytes_in_use": stats["peak_bytes_in_use"],
            "bytes_limit": limit,
            "fraction": round(fraction, 6) if fraction is not None
            else None,
            "source": stats["source"],
            "pools": dict(pools, other=max(in_use - tagged, 0)),
        }
        trigger = None
        with self._lock:
            self.snapshots += 1
            self._ring.append(rec)
            if self._baseline is None:
                self._baseline = dict(rec["pools"])
            wm = self.watermarks
            wm["peak_bytes_in_use"] = max(wm["peak_bytes_in_use"], in_use)
            if fraction is not None:
                wm["peak_fraction"] = max(wm["peak_fraction"], fraction)
            for name, b in rec["pools"].items():
                wm["pools"][name] = max(wm["pools"].get(name, 0), b)
            if fraction is not None and \
                    fraction >= self.config.watermark and \
                    "near_oom" not in self._latched:
                self._latched.add("near_oom")
                trigger = {
                    "fraction": round(fraction, 6),
                    "watermark": self.config.watermark,
                    "bytes_in_use": in_use,
                    "bytes_limit": limit,
                    "pool": self._growth_culprit_locked(rec["pools"]),
                    "pools": dict(rec["pools"]),
                }
            wm_pools = dict(wm["pools"])
            wm_peak = wm["peak_bytes_in_use"]
        for name, b in sorted(rec["pools"].items()):
            _instr.record_mem_bytes_in_use(name, b)
        _instr.record_mem_bytes_in_use("total", in_use)
        for name, b in sorted(wm_pools.items()):
            _instr.record_mem_peak_bytes(name, b)
        _instr.record_mem_peak_bytes("total", wm_peak)
        if fraction is not None:
            _instr.record_mem_watermark_fraction(fraction)
        if trigger is not None:
            # dump AFTER the triggering snapshot landed in the ring, so
            # the dump's last record is the one that explains it (the
            # PR 9 flush-after-step discipline)
            self.dump(reason="near_oom", detail=trigger)
        return rec

    def _growth_culprit_locked(self, pools: Dict[str, int]) -> str:
        """The pool whose growth since the FIRST snapshot is largest —
        what filled the chip, not what merely sat on it. Ties break by
        current bytes, then name (deterministic for the drill)."""
        base = self._baseline or {}
        ranked = sorted(
            ((b - base.get(name, 0), b, name)
             for name, b in pools.items()),
            key=lambda t: (-t[0], -t[1], t[2]))
        return ranked[0][2] if ranked else "other"

    # -- watermarks -----------------------------------------------------------
    def reset_watermarks(self) -> None:
        """Clear the per-pool and overall high watermarks AND the
        device-level peak counters (``device.reset_peak_memory_stats``),
        so per-phase peaks — warm start vs steady state, prefill vs
        decode — are measurable from a clean floor."""
        with self._lock:
            self.watermarks = {"peak_bytes_in_use": 0,
                               "peak_fraction": 0.0, "pools": {}}
            self._baseline = None
        try:
            from .. import device as _device
            _device.reset_peak_memory_stats(self.config.device)
        except Exception:  # noqa: BLE001 — reset is advisory
            logger.debug("memwatch: device peak reset unavailable",
                         exc_info=True)

    def reset_triggers(self) -> None:
        """Re-arm latched pressure-dump reasons (tests / long-lived
        processes that rotated their dump file)."""
        with self._lock:
            self._latched.clear()

    # -- flight dump ----------------------------------------------------------
    def dump(self, reason: str = "manual", detail: Optional[dict] = None,
             path: Optional[str] = None) -> Optional[Dict[str, Any]]:
        """Dump the memory ring; returns the record dict, or None on
        failure. NEVER raises — a dump triggered by memory pressure must
        not become the allocation that tips the process over."""
        try:
            with self._lock:
                rec = self._dump_record_locked(reason, detail)
                target = path if path is not None else self.dump_path
                if target:
                    _atomic_json(target, rec, indent=1)
                self.dumps.append({"reason": reason,
                                   "unix_time": rec["unix_time"],
                                   "path": target or None})
            _instr.record_mem_pressure_dump(reason)
            logger.info("memwatch: dump (%s)%s", reason,
                        f" -> {target}" if target else "")
            return rec
        except Exception:  # noqa: BLE001 — dump-on-pressure must not raise
            with self._lock:
                self.dump_failures += 1
            logger.warning("memwatch: dump failed (reason=%s)", reason,
                           exc_info=True)
            return None

    def _dump_record_locked(self, reason: str,
                            detail: Optional[dict]) -> Dict[str, Any]:
        if self._identity is None:
            from .evidence import device_identity
            self._identity = device_identity()
        return {
            "version": 1,
            "kind": "memwatch",
            "reason": reason,
            "detail": detail,
            "unix_time": self._wall(time.monotonic()),
            "device_kind": self._identity[0],
            "platform": self._identity[1],
            "ring": {"ring_steps": self.config.ring_steps,
                     "watermark": self.config.watermark},
            "steps": list(self._ring),
            "watermarks": json.loads(json.dumps(self.watermarks)),
            "counters": {"snapshots": self.snapshots,
                         "snapshot_failures": self.snapshot_failures,
                         "dump_failures": self.dump_failures},
        }

    # -- telemetry ------------------------------------------------------------
    def telemetry(self) -> Dict[str, Any]:
        """Snapshot for ``engine.telemetry()`` / dashboards: the last
        ring record, watermarks, and dump status."""
        with self._lock:
            return {
                "last": dict(self._ring[-1]) if self._ring else None,
                "watermarks": json.loads(json.dumps(self.watermarks)),
                "snapshots": self.snapshots,
                "snapshot_failures": self.snapshot_failures,
                "dumps": list(self.dumps),
                "dump_failures": self.dump_failures,
            }


def resolve_watcher(spec) -> Optional[MemoryWatcher]:
    """Normalize a ``memwatch`` argument: a watcher passes through, a
    MemWatchConfig builds one, True arms the defaults, False disarms,
    and None defers to the env (``PADDLE_MEMWATCH`` truthy, or a
    ``PADDLE_MEMWATCH_DUMP`` file being named, arms)."""
    if spec is None:
        if os.environ.get(ENV_MEMWATCH, "").strip().lower() in _TRUTHY \
                or os.environ.get(ENV_DUMP, "").strip():
            return MemoryWatcher()
        return None
    if spec is False:
        return None
    if spec is True:
        return MemoryWatcher()
    if isinstance(spec, MemWatchConfig):
        return MemoryWatcher(spec)
    if isinstance(spec, MemoryWatcher):
        return spec
    raise TypeError(
        f"memwatch wants None/bool/MemWatchConfig/MemoryWatcher, "
        f"got {type(spec).__name__}")


__all__ = ["MemWatchConfig", "MemoryWatcher", "resolve_watcher",
           "tree_bytes", "POOLS", "ENV_MEMWATCH", "ENV_DUMP",
           "ENV_WATERMARK"]
