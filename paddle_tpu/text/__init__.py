"""paddle.text namespace: Viterbi decoding for CRF-style taggers.

Reference parity: python/paddle/text/viterbi_decode.py (op) +
phi/kernels/cpu/viterbi_decode_kernel.cc (semantics: transitions row N-1 is
the start tag's outgoing transitions, row N-2 the stop tag's; both applied
only when include_bos_eos_tag=True).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops.dispatch import dispatch, ensure_tensor

__all__ = ["viterbi_decode", "ViterbiDecoder", "Conll05st", "Imdb",
           "Imikolov", "Movielens", "UCIHousing", "WMT14", "WMT16"]


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """Max-score tag path per sequence.

    potentials: [B, T, N] unary emission scores; transition_params: [N, N];
    lengths: [B] int. Returns (scores [B], paths [B, T] int64 — entries past
    a sequence's length are 0).
    """
    pt = ensure_tensor(potentials)
    tt = ensure_tensor(transition_params)
    lt = ensure_tensor(lengths)

    def fwd(pot, trans, lens):
        pot = pot.astype(jnp.float32)
        trans = trans.astype(jnp.float32)
        B, T, N = pot.shape
        lens = lens.astype(jnp.int32)
        alpha = pot[:, 0, :]
        if include_bos_eos_tag:
            alpha = alpha + trans[N - 1][None, :]
            alpha = alpha + jnp.where((lens == 1)[:, None],
                                      trans[N - 2][None, :], 0.0)

        def step(carry, inp):
            alpha, t = carry
            logit_t = inp
            # alpha_trn[b, i, j] = alpha[b, i] + trans[i, j]
            trn = alpha[:, :, None] + trans[None, :, :]
            hist = jnp.argmax(trn, axis=1)              # [B, N]
            amax = jnp.max(trn, axis=1)
            nxt = amax + logit_t
            if include_bos_eos_tag:
                nxt = nxt + jnp.where((t == lens - 1)[:, None],
                                      trans[N - 2][None, :], 0.0)
            live = (t < lens)[:, None]
            alpha = jnp.where(live, nxt, alpha)
            return (alpha, t + 1), hist

        (alpha, _), historys = jax.lax.scan(
            step, (alpha, jnp.int32(1)), jnp.moveaxis(pot[:, 1:, :], 1, 0))
        scores = jnp.max(alpha, axis=-1)
        last_tag = jnp.argmax(alpha, axis=-1).astype(jnp.int32)   # [B]

        # backtrack: walk historys from the end; positions past len-1 keep
        # propagating last_tag (their history rows were never applied)
        def back(tag, inp):
            hist, t = inp
            prev = jnp.take_along_axis(hist, tag[:, None], axis=1)[:, 0]
            tag_new = jnp.where(t < lens - 1, prev, tag)
            return tag_new.astype(jnp.int32), tag_new.astype(jnp.int32)

        ts = jnp.arange(T - 2, -1, -1, dtype=jnp.int32)
        _, rev_tags = jax.lax.scan(back, last_tag,
                                   (historys[::-1], ts))
        # paths[t] for t in 0..T-2 from rev_tags reversed; path[len-1]=last_tag
        path_head = rev_tags[::-1]                     # [T-1, B]
        full = jnp.concatenate([path_head,
                                jnp.zeros((1, B), jnp.int32)], axis=0)
        t_grid = jnp.arange(T)[:, None]
        full = jnp.where(t_grid == (lens - 1)[None, :], last_tag[None, :],
                         full)
        full = jnp.where(t_grid < lens[None, :], full, 0)
        return scores, jnp.moveaxis(full, 0, 1).astype(jnp.int64)

    return dispatch("viterbi_decode", fwd, pt, tt, lt)


class ViterbiDecoder:
    """Parity: paddle.text.ViterbiDecoder."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


def crf_decoding(potentials, transition_params, lengths,
                 include_bos_eos_tag=True, name=None):
    """Legacy alias of viterbi_decode (parity: crf_decoding op)."""
    return viterbi_decode(potentials, transition_params, lengths,
                          include_bos_eos_tag, name)


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None, name=None):
    """Levenshtein distance between id sequences (parity: edit_distance op).
    input/label: [B, L] padded int tensors; returns (distance [B, 1],
    sequence_num [1]). Host-side eager DP (data-dependent trip counts)."""
    import numpy as np

    from ..ops.dispatch import ensure_tensor
    import jax.numpy as jnp

    a = np.asarray(ensure_tensor(input).numpy())
    b = np.asarray(ensure_tensor(label).numpy())
    il = (np.asarray(ensure_tensor(input_length).numpy()).reshape(-1)
          if input_length is not None else
          np.full(a.shape[0], a.shape[1], np.int64))
    ll = (np.asarray(ensure_tensor(label_length).numpy()).reshape(-1)
          if label_length is not None else
          np.full(b.shape[0], b.shape[1], np.int64))
    ignored = set(ignored_tokens or [])
    out = np.zeros((a.shape[0], 1), np.float32)
    for i in range(a.shape[0]):
        s = [t for t in a[i, :il[i]].tolist() if t not in ignored]
        t = [u for u in b[i, :ll[i]].tolist() if u not in ignored]
        m, n = len(s), len(t)
        dp = np.arange(n + 1, dtype=np.float32)
        for x in range(1, m + 1):
            prev = dp.copy()
            dp[0] = x
            for y in range(1, n + 1):
                dp[y] = min(prev[y] + 1, dp[y - 1] + 1,
                            prev[y - 1] + (s[x - 1] != t[y - 1]))
        d = dp[n]
        if normalized:
            d = d / max(n, 1)
        out[i, 0] = d
    from ..tensor import Tensor
    return (Tensor(jnp.asarray(out)),
            Tensor(jnp.asarray(np.asarray([a.shape[0]], np.int64))))


__all__ += ["crf_decoding", "edit_distance"]


from .datasets import (  # noqa: F401, E402
    Conll05st, Imdb, Imikolov, Movielens, UCIHousing, WMT14, WMT16,
)
