"""paddle.text datasets (reference python/paddle/text/datasets/): the
classic benchmark corpora. Local files parse the REAL formats
(whitespace housing rows, Imikolov n-grams, Movielens ratings, Imdb
token files, WMT parallel pairs, Conll05 column format); without a
local file the datasets synthesize format-identical data — this
environment has no network egress, and the reference's downloader is
the only part that needs it."""
from __future__ import annotations

import os
import re
import tarfile

import numpy as np

from ..io import Dataset


class UCIHousing(Dataset):
    """Parity: text.datasets.UCIHousing — 13 features -> house price,
    feature-normalized like the reference loader."""

    N_FEAT = 13

    def __init__(self, data_file=None, mode="train", download=False,
                 synthetic_size=404):
        if data_file and os.path.exists(data_file):
            raw = np.loadtxt(data_file).astype(np.float32)
        else:
            rng = np.random.default_rng(0)
            x = rng.standard_normal((synthetic_size + 102, self.N_FEAT))
            w = rng.standard_normal(self.N_FEAT)
            y = (x @ w + rng.standard_normal(x.shape[0]) * 0.1)[:, None]
            raw = np.concatenate([x, y], axis=1).astype(np.float32)
        mins, maxs = raw.min(0), raw.max(0)
        feat = raw[:, :-1]
        feat = (feat - feat.mean(0)) / np.maximum(
            maxs[:-1] - mins[:-1], 1e-6)
        raw = np.concatenate([feat, raw[:, -1:]], axis=1)
        split = int(len(raw) * 0.8)
        self.data = raw[:split] if mode == "train" else raw[split:]

    def __getitem__(self, idx):
        row = self.data[idx]
        return row[:-1], row[-1:]

    def __len__(self):
        return len(self.data)


class Imikolov(Dataset):
    """Parity: text.datasets.Imikolov — PTB-style n-gram language-model
    samples with a frequency-built word dict."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=1, download=False,
                 synthetic_size=2000):
        self.window = window_size
        self.type = data_type.upper()
        if data_file and os.path.exists(data_file):
            with open(data_file) as f:
                lines = [ln.strip().split() for ln in f if ln.strip()]
        else:
            rng = np.random.default_rng(1 if mode == "train" else 2)
            vocab = [f"w{i}" for i in range(50)]
            lines = [[vocab[int(j)] for j in
                      rng.integers(0, 50, rng.integers(3, 12))]
                     for _ in range(synthetic_size // 4)]
        freq = {}
        for ln in lines:
            for w in ln:
                freq[w] = freq.get(w, 0) + 1
        words = sorted([w for w, c in freq.items() if c >= min_word_freq],
                       key=lambda w: (-freq[w], w))
        self.word_idx = {w: i for i, w in enumerate(words)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.data = []
        for ln in lines:
            ids = [self.word_idx.get(w, unk) for w in ln]
            if self.type == "NGRAM":
                for i in range(len(ids) - window_size + 1):
                    self.data.append(
                        np.asarray(ids[i:i + window_size], np.int64))
            else:                                  # SEQ: (src, trg) shift
                if len(ids) >= 2:
                    self.data.append((np.asarray(ids[:-1], np.int64),
                                      np.asarray(ids[1:], np.int64)))

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class Imdb(Dataset):
    """Parity: text.datasets.Imdb — sentiment-labeled token-id docs with
    a frequency dict (reads an aclImdb tar when given)."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=False, synthetic_size=512):
        docs = []
        labels = []
        if data_file and os.path.exists(data_file):
            pat = re.compile(rf"aclImdb/{mode}/(pos|neg)/.*\.txt$")
            with tarfile.open(data_file) as tf:
                for m in tf.getmembers():
                    g = pat.match(m.name)
                    if not g:
                        continue
                    text = tf.extractfile(m).read().decode(
                        "utf-8", "ignore").lower()
                    docs.append(re.findall(r"[a-z]+", text))
                    labels.append(0 if g.group(1) == "pos" else 1)
        else:
            rng = np.random.default_rng(2 if mode == "train" else 3)
            pos_v = [f"good{i}" for i in range(20)]
            neg_v = [f"bad{i}" for i in range(20)]
            common = [f"the{i}" for i in range(30)]
            for _ in range(synthetic_size):
                y = int(rng.integers(0, 2))
                bank = (pos_v if y == 0 else neg_v) + common
                docs.append([bank[int(j)] for j in
                             rng.integers(0, len(bank),
                                          rng.integers(5, 30))])
                labels.append(y)
        freq = {}
        for d in docs:
            for w in d:
                freq[w] = freq.get(w, 0) + 1
        words = sorted([w for w, c in freq.items() if c >= min(
            cutoff, max(freq.values()))] or list(freq),
            key=lambda w: (-freq[w], w))
        self.word_idx = {w: i for i, w in enumerate(words)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.docs = [np.asarray([self.word_idx.get(w, unk) for w in d],
                                np.int64) for d in docs]
        self.labels = np.asarray(labels, np.int64)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Movielens(Dataset):
    """Parity: text.datasets.Movielens — (user features, movie features,
    rating) tuples from the ml-1m layout (ratings.dat / users.dat /
    movies.dat inside the archive or dir)."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=False, synthetic_size=2048):
        rng = np.random.default_rng(rand_seed)
        if data_file and os.path.isdir(data_file):
            def read(name):
                with open(os.path.join(data_file, name),
                          encoding="latin-1") as f:
                    return [ln.strip().split("::") for ln in f if ln.strip()]
            ratings = [(int(u), int(m), float(r))
                       for u, m, r, _t in read("ratings.dat")]
        else:
            ratings = [(int(rng.integers(1, 500)),
                        int(rng.integers(1, 300)),
                        float(rng.integers(1, 6)))
                       for _ in range(synthetic_size)]
        mask = rng.random(len(ratings)) < test_ratio
        keep = [r for r, m in zip(ratings, mask)
                if (m if mode == "test" else not m)]
        self.samples = [(np.asarray([u], np.int64),
                         np.asarray([m], np.int64),
                         np.asarray([r], np.float32))
                        for u, m, r in keep]

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class _WMTBase(Dataset):
    """Shared WMT parallel-corpus machinery: (src ids, trg ids,
    trg_next ids) with <s>/<e>/<unk> specials, dict capped at dict_size."""

    def __init__(self, src_lines, trg_lines, dict_size):
        def build(lines):
            freq = {}
            for ln in lines:
                for w in ln:
                    freq[w] = freq.get(w, 0) + 1
            words = sorted(freq, key=lambda w: (-freq[w], w))
            vocab = ["<s>", "<e>", "<unk>"] + words[:max(dict_size - 3, 0)]
            return {w: i for i, w in enumerate(vocab)}
        self.src_dict = build(src_lines)
        self.trg_dict = build(trg_lines)
        s_unk, t_unk = self.src_dict["<unk>"], self.trg_dict["<unk>"]
        self.samples = []
        for s, t in zip(src_lines, trg_lines):
            sid = [self.src_dict.get(w, s_unk) for w in s]
            tid = ([self.trg_dict["<s>"]]
                   + [self.trg_dict.get(w, t_unk) for w in t])
            self.samples.append((np.asarray(sid, np.int64),
                                 np.asarray(tid, np.int64),
                                 np.asarray(tid[1:] + [self.trg_dict["<e>"]],
                                            np.int64)))

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


def _wmt_synthetic(mode, n):
    rng = np.random.default_rng(4 if mode == "train" else 5)
    src_v = [f"de{i}" for i in range(80)]
    trg_v = [f"en{i}" for i in range(80)]
    src, trg = [], []
    for _ in range(n):
        ln = int(rng.integers(3, 12))
        idx = rng.integers(0, 80, ln)
        src.append([src_v[int(i)] for i in idx])
        trg.append([trg_v[int(i)] for i in idx])   # aligned toy pairs
    return src, trg


def _wmt_from_file(path, mode):
    """Two aligned plain-text files '<path>.src'/'<path>.trg', or a
    single tab-separated file."""
    if os.path.exists(str(path) + ".src"):
        with open(str(path) + ".src") as f:
            src = [ln.split() for ln in f if ln.strip()]
        with open(str(path) + ".trg") as f:
            trg = [ln.split() for ln in f if ln.strip()]
        return src, trg
    src, trg = [], []
    with open(path) as f:
        for ln in f:
            if "\t" in ln:
                a, b = ln.rstrip("\n").split("\t", 1)
                src.append(a.split())
                trg.append(b.split())
    return src, trg


class WMT14(_WMTBase):
    """Parity: text.datasets.WMT14."""

    def __init__(self, data_file=None, mode="train", dict_size=1000,
                 download=False, synthetic_size=256):
        if data_file and os.path.exists(data_file):
            src, trg = _wmt_from_file(data_file, mode)
        else:
            src, trg = _wmt_synthetic(mode, synthetic_size)
        super().__init__(src, trg, dict_size)


class WMT16(_WMTBase):
    """Parity: text.datasets.WMT16."""

    def __init__(self, data_file=None, mode="train", src_dict_size=1000,
                 trg_dict_size=1000, lang="en", download=False,
                 synthetic_size=256):
        if data_file and os.path.exists(data_file):
            src, trg = _wmt_from_file(data_file, mode)
        else:
            src, trg = _wmt_synthetic(mode, synthetic_size)
        super().__init__(src, trg, max(src_dict_size, trg_dict_size))


class Conll05st(Dataset):
    """Parity: text.datasets.Conll05st (semantic role labeling):
    column-format sentences -> (word ids, predicate id, label ids)."""

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None, mode="train",
                 download=False, synthetic_size=200):
        sents = []
        if data_file and os.path.exists(data_file):
            cur_w, cur_l = [], []
            with open(data_file) as f:
                for ln in f:
                    ln = ln.strip()
                    if not ln:
                        if cur_w:
                            sents.append((cur_w, cur_l))
                        cur_w, cur_l = [], []
                        continue
                    parts = ln.split()
                    cur_w.append(parts[0])
                    cur_l.append(parts[-1])
            if cur_w:
                sents.append((cur_w, cur_l))
        else:
            rng = np.random.default_rng(6)
            vocab = [f"tok{i}" for i in range(60)]
            tags = ["B-A0", "I-A0", "B-V", "O"]
            for _ in range(synthetic_size):
                n = int(rng.integers(4, 15))
                sents.append((
                    [vocab[int(i)] for i in rng.integers(0, 60, n)],
                    [tags[int(i)] for i in rng.integers(0, 4, n)]))
        words = sorted({w for s, _ in sents for w in s})
        labels = sorted({t for _, ls in sents for t in ls})
        self.word_dict = {w: i for i, w in enumerate(words)}
        self.label_dict = {t: i for i, t in enumerate(labels)}
        self.samples = []
        for ws, ls in sents:
            wid = np.asarray([self.word_dict[w] for w in ws], np.int64)
            lid = np.asarray([self.label_dict[t] for t in ls], np.int64)
            verb = int(np.argmax(lid == self.label_dict.get("B-V", 0))) \
                if len(lid) else 0
            self.samples.append((wid, np.asarray([verb], np.int64), lid))

    def get_dict(self):
        return self.word_dict, {"B-V": 0}, self.label_dict

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


__all__ = ["Conll05st", "Imdb", "Imikolov", "Movielens", "UCIHousing",
           "WMT14", "WMT16"]
