"""High-level-API callbacks (parity: python/paddle/hapi/callbacks.py —
Callback protocol, ProgBarLogger, ModelCheckpoint, EarlyStopping,
LRScheduler; VisualDL is stubbed since the viz package is external)."""
from __future__ import annotations

import os
import time
from typing import List, Optional


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = dict(params or {})

    def on_train_begin(self, logs=None): ...
    def on_train_end(self, logs=None): ...
    def on_eval_begin(self, logs=None): ...
    def on_eval_end(self, logs=None): ...
    def on_predict_begin(self, logs=None): ...
    def on_predict_end(self, logs=None): ...
    def on_epoch_begin(self, epoch, logs=None): ...
    def on_epoch_end(self, epoch, logs=None): ...
    def on_train_batch_begin(self, step, logs=None): ...
    def on_train_batch_end(self, step, logs=None): ...
    def on_eval_batch_begin(self, step, logs=None): ...
    def on_eval_batch_end(self, step, logs=None): ...
    def on_predict_batch_begin(self, step, logs=None): ...
    def on_predict_batch_end(self, step, logs=None): ...


class CallbackList:
    def __init__(self, callbacks: Optional[List[Callback]] = None):
        self.callbacks = list(callbacks or [])

    def append(self, cb):
        self.callbacks.append(cb)

    def set_model(self, model):
        for cb in self.callbacks:
            cb.set_model(model)

    def set_params(self, params):
        for cb in self.callbacks:
            cb.set_params(params)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def fire(*args, **kwargs):
                for cb in self.callbacks:
                    getattr(cb, name)(*args, **kwargs)
            return fire
        raise AttributeError(name)


class ProgBarLogger(Callback):
    """Prints loss/metrics every `log_freq` steps and per epoch."""

    def __init__(self, log_freq: int = 10, verbose: int = 2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch
        self._start = time.time()
        if self.verbose:
            total = self.params.get("epochs")
            print(f"Epoch {epoch + 1}/{total}")

    def _fmt(self, logs):
        return " - ".join(f"{k}: {v:.4f}" if isinstance(v, float)
                          else f"{k}: {v}" for k, v in (logs or {}).items())

    def on_train_batch_end(self, step, logs=None):
        if self.verbose > 1 and self.log_freq and \
                (step + 1) % self.log_freq == 0:
            print(f"step {step + 1}: {self._fmt(logs)}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._start
            print(f"epoch {epoch + 1} done in {dt:.1f}s - {self._fmt(logs)}")

    def on_eval_end(self, logs=None):
        if self.verbose:
            print(f"Eval - {self._fmt(logs)}")


class ModelCheckpoint(Callback):
    """Saves `{save_dir}/{epoch}` every `save_freq` epochs + `final`."""

    def __init__(self, save_freq: int = 1, save_dir: str = "checkpoint"):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.model is not None and (epoch + 1) % self.save_freq == 0:
            self.model.save(os.path.join(self.save_dir, str(epoch)))

    def on_train_end(self, logs=None):
        if self.model is not None:
            self.model.save(os.path.join(self.save_dir, "final"))


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler each batch and/or epoch."""

    def __init__(self, by_step: bool = True, by_epoch: bool = False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "step") else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        self.stopped_epoch = 0
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.best = None
        self.wait = 0
        self.stop_training = False

    def _better(self, cur, best):
        if self.mode == "min":
            return cur < best - self.min_delta
        return cur > best + self.min_delta

    def on_eval_end(self, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:  # eval logs carry an eval_ prefix
            cur = logs.get("eval_" + self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        cur = float(cur)
        if self.best is None or self._better(cur, self.best):
            self.best = cur
            self.wait = 0
            if self.save_best_model and self.model is not None and \
                    getattr(self.model, "_save_dir", None):
                self.model.save(os.path.join(self.model._save_dir,
                                             "best_model"))
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stop_training = True
                if self.verbose:
                    print(f"EarlyStopping: no {self.monitor} improvement for "
                          f"{self.wait} evals (best {self.best:.4f})")


class VisualDL(Callback):  # pragma: no cover - external viz package
    def __init__(self, log_dir="vdl_log"):
        super().__init__()
        self.log_dir = log_dir

    def on_train_batch_end(self, step, logs=None):
        pass
