"""High-level-API callbacks (parity: python/paddle/hapi/callbacks.py —
Callback protocol, ProgBarLogger, ModelCheckpoint, EarlyStopping,
LRScheduler; VisualDL is stubbed since the viz package is external)."""
from __future__ import annotations

import os
import time
from typing import List, Optional


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = dict(params or {})

    def on_train_begin(self, logs=None): ...
    def on_train_end(self, logs=None): ...
    def on_eval_begin(self, logs=None): ...
    def on_eval_end(self, logs=None): ...
    def on_predict_begin(self, logs=None): ...
    def on_predict_end(self, logs=None): ...
    def on_epoch_begin(self, epoch, logs=None): ...
    def on_epoch_end(self, epoch, logs=None): ...
    def on_train_batch_begin(self, step, logs=None): ...
    def on_train_batch_end(self, step, logs=None): ...
    def on_eval_batch_begin(self, step, logs=None): ...
    def on_eval_batch_end(self, step, logs=None): ...
    def on_predict_batch_begin(self, step, logs=None): ...
    def on_predict_batch_end(self, step, logs=None): ...


class CallbackList:
    def __init__(self, callbacks: Optional[List[Callback]] = None):
        self.callbacks = list(callbacks or [])

    def append(self, cb):
        self.callbacks.append(cb)

    def set_model(self, model):
        for cb in self.callbacks:
            cb.set_model(model)

    def set_params(self, params):
        for cb in self.callbacks:
            cb.set_params(params)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def fire(*args, **kwargs):
                for cb in self.callbacks:
                    getattr(cb, name)(*args, **kwargs)
            return fire
        raise AttributeError(name)


class ProgBarLogger(Callback):
    """Prints loss/metrics every `log_freq` steps and per epoch."""

    def __init__(self, log_freq: int = 10, verbose: int = 2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch
        self._start = time.time()
        if self.verbose:
            total = self.params.get("epochs")
            print(f"Epoch {epoch + 1}/{total}")

    def _fmt(self, logs):
        return " - ".join(f"{k}: {v:.4f}" if isinstance(v, float)
                          else f"{k}: {v}" for k, v in (logs or {}).items())

    def on_train_batch_end(self, step, logs=None):
        if self.verbose > 1 and self.log_freq and \
                (step + 1) % self.log_freq == 0:
            print(f"step {step + 1}: {self._fmt(logs)}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._start
            print(f"epoch {epoch + 1} done in {dt:.1f}s - {self._fmt(logs)}")

    def on_eval_end(self, logs=None):
        if self.verbose:
            print(f"Eval - {self._fmt(logs)}")


class ModelCheckpoint(Callback):
    """Saves `{save_dir}/{epoch}` every `save_freq` epochs + `final`."""

    def __init__(self, save_freq: int = 1, save_dir: str = "checkpoint"):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.model is not None and (epoch + 1) % self.save_freq == 0:
            self.model.save(os.path.join(self.save_dir, str(epoch)))

    def on_train_end(self, logs=None):
        if self.model is not None:
            self.model.save(os.path.join(self.save_dir, "final"))


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler each batch and/or epoch."""

    def __init__(self, by_step: bool = True, by_epoch: bool = False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "step") else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        self.stopped_epoch = 0
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.best = None
        self.wait = 0
        self.stop_training = False

    def _better(self, cur, best):
        if self.mode == "min":
            return cur < best - self.min_delta
        return cur > best + self.min_delta

    def on_eval_end(self, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:  # eval logs carry an eval_ prefix
            cur = logs.get("eval_" + self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        cur = float(cur)
        if self.best is None or self._better(cur, self.best):
            self.best = cur
            self.wait = 0
            if self.save_best_model and self.model is not None and \
                    getattr(self.model, "_save_dir", None):
                self.model.save(os.path.join(self.model._save_dir,
                                             "best_model"))
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stop_training = True
                if self.verbose:
                    print(f"EarlyStopping: no {self.monitor} improvement for "
                          f"{self.wait} evals (best {self.best:.4f})")


class VisualDL(Callback):  # pragma: no cover - external viz package
    def __init__(self, log_dir="vdl_log"):
        super().__init__()
        self.log_dir = log_dir

    def on_train_batch_end(self, step, logs=None):
        pass


class ReduceLROnPlateau(Callback):
    """Parity: hapi ReduceLROnPlateau — shrink the optimizer lr when the
    monitored metric plateaus (wraps the optimizer's plain-float lr; if a
    scheduler is installed this callback leaves it alone)."""

    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0):
        super().__init__()
        self.monitor = monitor
        self.factor = float(factor)
        if self.factor >= 1.0:
            raise ValueError("factor must be < 1.0")
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.cooldown = cooldown
        self.min_lr = min_lr
        if mode == "max" or (mode == "auto" and "acc" in monitor):
            self._better = lambda cur, best: cur > best + self.min_delta
            self._best0 = -float("inf")
        else:
            self._better = lambda cur, best: cur < best - self.min_delta
            self._best0 = float("inf")
        self._best = self._best0
        self._wait = 0
        self._cooldown_counter = 0

    def _get_metric(self, logs):
        logs = logs or {}
        v = logs.get(self.monitor)
        if v is None:
            # eval logs carry an "eval_" prefix (same fallback as
            # EarlyStopping above)
            v = logs.get("eval_" + self.monitor)
        if isinstance(v, (list, tuple)):
            v = v[0]
        return v

    def on_eval_end(self, logs=None):
        self._check(self._get_metric(logs))

    def on_epoch_end(self, epoch, logs=None):
        v = self._get_metric(logs)
        if v is not None:
            self._check(v)

    def _check(self, current):
        if current is None:
            return
        opt = getattr(self.model, "_optimizer", None)
        if opt is None:
            return
        if self._cooldown_counter > 0:
            # in cooldown: consume an epoch, track bests, do NOT count waits
            self._cooldown_counter -= 1
            self._wait = 0
            if self._better(current, self._best):
                self._best = current
            return
        if self._better(current, self._best):
            self._best = current
            self._wait = 0
            return
        self._wait += 1
        if self._wait >= self.patience:
            from ..optimizer.lr import LRScheduler
            if isinstance(opt._learning_rate, LRScheduler):
                return  # scheduler owns the lr
            old = float(opt.get_lr())
            new = max(old * self.factor, self.min_lr)
            if old - new > 1e-12:
                opt.set_lr(new)
                if self.verbose:
                    print(f"ReduceLROnPlateau: lr {old:.3e} -> {new:.3e}")
            self._cooldown_counter = self.cooldown
            self._wait = 0


class WandbCallback(Callback):
    """Parity: hapi WandbCallback — metric logging to Weights & Biases.
    Requires the external `wandb` package; constructing without it raises
    (the reference behaves the same way)."""

    def __init__(self, project=None, name=None, dir=None, mode=None, **kw):
        super().__init__()
        try:
            import wandb
        except ImportError as e:
            raise ImportError(
                "WandbCallback requires the wandb package (pip install "
                "wandb); it is not bundled in this environment") from e
        self._wandb = wandb
        self._init_kw = dict(project=project, name=name, dir=dir, mode=mode,
                             **kw)
        self._run = None

    def on_train_begin(self, logs=None):
        # start the (network-backed) run lazily per fit(), so construction
        # is side-effect free and the callback is reusable across fits
        if self._run is None:
            self._run = self._wandb.init(**self._init_kw)

    def on_epoch_end(self, epoch, logs=None):
        if self._run is None:
            return
        payload = {k: (v[0] if isinstance(v, (list, tuple)) else v)
                   for k, v in (logs or {}).items()}
        payload["epoch"] = epoch
        self._run.log(payload)

    def on_train_end(self, logs=None):
        if self._run is not None:
            self._run.finish()
            self._run = None
