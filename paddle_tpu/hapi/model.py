"""Keras-like Model API.

Reference parity: paddle.Model (python/paddle/hapi/model.py:1472; fit :2200,
DynamicGraphAdapter :1196). TPU-native: one adapter — eager model code, with
`prepare(jit=True)` routing train/eval batches through `jit.to_static` so
the whole step compiles to a single XLA program (the reference's
static-graph adapter, done the trace-and-compile way).
"""
from __future__ import annotations

import time
from typing import List, Optional, Sequence

import numpy as np

from .. import optimizer as opt_mod
from .. import profiler as _prof
from ..profiler import TracerEventType as _Ev
from ..profiler import instrument as _instr
from ..resilience import chaos as _chaos
from ..io import DataLoader, Dataset
from ..metric import Metric
from ..tensor import Tensor, to_tensor
from .callbacks import Callback, CallbackList, ModelCheckpoint, ProgBarLogger


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _tensorize(batch):
    if isinstance(batch, (list, tuple)):
        return [b if isinstance(b, Tensor) else to_tensor(b) for b in batch]
    return [batch if isinstance(batch, Tensor) else to_tensor(batch)]


_FIT_END = object()  # loader-exhausted sentinel for the instrumented fetch


def _batch_tokens(inputs) -> Optional[int]:
    """Element count of the first input (B*T for token models), for
    runlog tokens/s."""
    try:
        first = inputs[0] if isinstance(inputs, (list, tuple)) else inputs
        n = 1
        for d in first.shape:
            n *= int(d)
        return n
    except Exception:  # noqa: BLE001
        return None


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self._jit = False
        self._compiled_step = None
        self._save_dir = None
        self.stop_training = False

    # -- setup ---------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None, jit: bool = False):
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = _to_list(metrics)
        for m in self._metrics:
            if not isinstance(m, Metric):
                raise TypeError(f"metric {m!r} is not a paddle.metric.Metric")
        if jit and not self._jit:
            # compile the network forward into one XLA program; backward
            # flows through the compiled node's vjp (trace-and-compile
            # analog of the reference's StaticGraphAdapter)
            from ..jit import to_static
            to_static(self.network)
        self._jit = self._jit or jit

    # -- single-batch ops ----------------------------------------------------
    def _forward_loss(self, inputs, labels):
        outputs = self.network(*inputs)
        outs = _to_list(outputs)
        loss = self._loss(*outs, *labels)
        return loss, outputs

    def train_batch(self, inputs, labels=None, update=True,
                    loss_scale=1.0, step_guard=None, step=None):
        self.network.train()
        if _chaos.enabled():
            _chaos.site("train.step")
        inputs = _tensorize(inputs)
        labels = _tensorize(labels) if labels is not None else []
        with _prof.RecordEvent("Forward", _Ev.Forward):
            loss, outputs = self._forward_loss(inputs, labels)
        if step_guard is not None:
            # guard BEFORE backward/update: a poisoned step must not touch
            # optimizer state (the sync this forces is the one the loss
            # logging below pays anyway)
            lossf = float(np.asarray(loss._data))
            if _chaos.enabled():
                lossf = _chaos.poison("train.loss", lossf)
            if step_guard.check(lossf, step=step) == "skip":
                self._optimizer.clear_grad()
                metrics = self._update_metrics(outputs, labels)
                return [lossf], metrics
        with _prof.RecordEvent("Backward", _Ev.Backward):
            (loss * loss_scale if loss_scale != 1.0 else loss).backward()
        if update:
            with _prof.RecordEvent("Optimization", _Ev.Optimization):
                self._optimizer.step()
                self._optimizer.clear_grad()
        if _instr._enabled[0]:
            _instr.record_train_step()
        metrics = self._update_metrics(outputs, labels)
        lossf = float(np.asarray(loss._data))
        if step_guard is None and _chaos.enabled():
            # keep the train.loss probe advancing (and its poison visible
            # in logs) on unguarded runs too, so an env-armed plan behaves
            # identically with and without a guard
            lossf = _chaos.poison("train.loss", lossf)
        return [lossf], metrics

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        from ..autograd import no_grad
        inputs = _tensorize(inputs)
        labels = _tensorize(labels) if labels is not None else []
        with no_grad(), _prof.RecordEvent("Forward", _Ev.Forward):
            loss, outputs = self._forward_loss(inputs, labels)
        metrics = self._update_metrics(outputs, labels)
        return [float(np.asarray(loss._data))], metrics

    def predict_batch(self, inputs):
        self.network.eval()
        from ..autograd import no_grad
        with no_grad():
            out = self.network(*_tensorize(inputs))
        return [np.asarray(o._data) for o in _to_list(out)]

    def _update_metrics(self, outputs, labels):
        res = []
        outs = _to_list(outputs)
        for m in self._metrics:
            vals = m.compute(outs[0], *labels) if labels else outs[0]
            if not isinstance(vals, tuple):
                vals = (vals,)
            m.update(*vals)
            res.append(m.accumulate())
        return res

    # -- loops ---------------------------------------------------------------
    def _loader(self, data, batch_size, shuffle, num_workers,
                drop_last=False):
        if data is None or isinstance(data, DataLoader):
            return data
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          num_workers=num_workers, drop_last=drop_last)

    def _metric_logs(self, loss, prefix=""):
        logs = {prefix + "loss": loss[0]}
        for m in self._metrics:
            names = m.name() if isinstance(m.name(), list) else [m.name()]
            accs = m.accumulate()
            accs = accs if isinstance(accs, list) else [accs]
            for n, a in zip(names, accs):
                logs[prefix + n] = a
        return logs

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None, runlog=None,
            step_guard=None, preempt_guard=None, checkpointer=None):
        """step_guard: an optional resilience.StepGuard checked on every
        step's loss before backward/update — "skip" drops the update (the
        whole accumulation window when accumulating), "abort" raises
        StepGuardAbort out of fit.

        preempt_guard: an optional resilience.PreemptionGuard polled at
        every step boundary; once any rank holds a preemption notice the
        loop performs a deadline-aware emergency save through
        `checkpointer` (skipping eval/metrics flush/end callbacks) and
        raises resilience.Preempted.

        checkpointer: an optional resilience.TieredCheckpointer driven at
        each step boundary (RAM snapshots every `memory_every` steps,
        async persistent saves every `persist_every`); its background
        saves are drained (join + verify + mark_good) before fit
        returns."""
        assert self._optimizer is not None and self._loss is not None, \
            "call prepare(optimizer, loss) first"
        rl = _prof.RunLog(runlog) if isinstance(runlog, str) else runlog
        self._save_dir = save_dir
        loader = self._loader(train_data, batch_size, shuffle, num_workers,
                              drop_last=drop_last)
        eval_loader = self._loader(eval_data, batch_size, False, num_workers)

        cbs = CallbackList([ProgBarLogger(log_freq, verbose=verbose)]
                           + _to_list(callbacks))
        if save_dir:
            cbs.append(ModelCheckpoint(save_freq, save_dir))
        cbs.set_model(self)
        cbs.set_params({"epochs": epochs, "verbose": verbose,
                        "metrics": ["loss"] + [m.name()
                                               for m in self._metrics]})
        cbs.on_train_begin()
        try:
            self._fit_loop(loader, eval_loader, cbs, epochs, eval_freq,
                           accumulate_grad_batches, num_iters, rl,
                           step_guard, preempt_guard, checkpointer)
        finally:
            if rl is not None and isinstance(runlog, str):
                rl.close()
            if checkpointer is not None:
                # even when leaving via StepGuardAbort/Preempted, finished
                # background writers must still be verified + marked good
                # (non-blocking: in-flight writers are left to atexit) —
                # the abort-recovery path reads the ledger next
                checkpointer.poll()
        if checkpointer is not None:
            checkpointer.wait()  # mark cadence saves good before returning
        cbs.on_train_end()

    def _fit_loop(self, loader, eval_loader, cbs, epochs, eval_freq,
                  accumulate_grad_batches, num_iters, rl,
                  step_guard=None, preempt_guard=None, checkpointer=None):
        steps_done = 0
        for epoch in range(epochs):
            for m in self._metrics:
                m.reset()
            cbs.on_epoch_begin(epoch)
            logs = {}
            pending_update = False
            window_poisoned = False
            data_iter = iter(loader)
            step = -1
            while True:
                # loader fetch under a Dataloader span (worker-thread spans
                # inside DataLoader land in the same shared buffer)
                with _prof.RecordEvent("Dataloader", _Ev.Dataloader):
                    batch = next(data_iter, _FIT_END)
                if batch is _FIT_END:
                    break
                step += 1
                if _instr._enabled[0]:
                    _instr.record_dataloader_batch()
                cbs.on_train_batch_begin(step)
                inputs, labels = self._split_batch(batch)
                update = (step + 1) % accumulate_grad_batches == 0
                t0 = time.perf_counter()
                with _prof.RecordEvent("ProfileStep", _Ev.ProfileStep):
                    # a skip poisons its whole accumulation window: later
                    # micro-batches still run (metrics/logs) but must not
                    # apply a partial, mis-scaled update at the boundary
                    loss, _ = self.train_batch(
                        inputs, labels,
                        update=update and not window_poisoned,
                        loss_scale=1.0 / accumulate_grad_batches,
                        step_guard=step_guard, step=steps_done)
                if step_guard is not None and \
                        step_guard.last_decision == "skip":
                    window_poisoned = True
                if rl is not None:
                    rl.log_step(
                        step=steps_done, loss=loss[0],
                        step_time_ms=(time.perf_counter() - t0) * 1e3,
                        tokens=_batch_tokens(inputs))
                if update and window_poisoned:
                    self._optimizer.clear_grad()  # drop the poisoned window
                    window_poisoned = False
                pending_update = not update
                logs = self._metric_logs(loss)
                cbs.on_train_batch_end(step, logs)
                steps_done += 1
                # cadence saves only at optimizer-update boundaries, or
                # accumulation would inflate the save rate by the window
                # size; step ids count loader (micro-)steps throughout
                if checkpointer is not None and update:
                    checkpointer.maybe_save(steps_done)
                # preemption is checked EVERY micro-batch — reaction
                # latency beats boundary alignment, and the state is
                # consistent mid-window (optimizer untouched; only the
                # partial gradient window is lost, as on any restart)
                if preempt_guard is not None and \
                        preempt_guard.should_stop(step=steps_done):
                    self._emergency_stop(preempt_guard, checkpointer,
                                         steps_done)
                if num_iters is not None and steps_done >= num_iters:
                    break
            if pending_update and not window_poisoned:
                # flush a partial accumulation window
                self._optimizer.step()
                self._optimizer.clear_grad()
            elif window_poisoned:
                self._optimizer.clear_grad()
            cbs.on_epoch_end(epoch, logs)
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                self._run_eval(eval_loader, cbs)
            if self.stop_training or any(
                    getattr(cb, "stop_training", False)
                    for cb in cbs.callbacks):
                break
            if num_iters is not None and steps_done >= num_iters:
                break

    def _emergency_stop(self, preempt_guard, checkpointer, steps_done):
        """Preemption notice at a step boundary: land the emergency
        checkpoint inside the grace window (all optional work — eval,
        metrics flush, end-of-training callbacks — is skipped by the
        raise) and surface resilience.Preempted to the caller, who maps
        it to PREEMPTED_EXIT_CODE for the supervisor."""
        from ..resilience.preempt import Preempted
        saved = None
        if checkpointer is not None:
            saved = checkpointer.emergency_save(
                steps_done, deadline=preempt_guard.remaining())
        raise Preempted(steps_done, saved_step=saved,
                        source=preempt_guard.source or "unknown")

    def _run_eval(self, loader, cbs):
        for m in self._metrics:
            m.reset()
        cbs.on_eval_begin()
        logs = {}
        data_iter = iter(loader)
        step = -1
        while True:
            with _prof.RecordEvent("Dataloader", _Ev.Dataloader):
                batch = next(data_iter, _FIT_END)
            if batch is _FIT_END:
                break
            step += 1
            if _instr._enabled[0]:
                _instr.record_dataloader_batch()
            cbs.on_eval_batch_begin(step)
            inputs, labels = self._split_batch(batch)
            loss, _ = self.eval_batch(inputs, labels)
            logs = self._metric_logs(loss, prefix="eval_")
            cbs.on_eval_batch_end(step, logs)
        cbs.on_eval_end(logs)
        return logs

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None):
        loader = self._loader(eval_data, batch_size, False, num_workers)
        cbs = CallbackList([ProgBarLogger(log_freq, verbose=verbose)]
                           + _to_list(callbacks))
        cbs.set_model(self)
        cbs.set_params({"verbose": verbose})
        return self._run_eval(loader, cbs)

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None):
        loader = self._loader(test_data, batch_size, False, num_workers)
        outputs = []
        for batch in loader:
            # datasets yielding (inputs..., label) keep working: the trailing
            # element is dropped, matching fit/evaluate's split
            inputs, _ = self._split_batch(batch)
            outputs.append(self.predict_batch(inputs))
        if stack_outputs and outputs:
            n_out = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs])
                    for i in range(n_out)]
        return outputs

    def _split_batch(self, batch, has_labels=True):
        if isinstance(batch, (list, tuple)) and len(batch) >= 2 and has_labels:
            return _to_list(batch[:-1]), _to_list(batch[-1])
        return _to_list(batch), []

    # -- persistence / info ---------------------------------------------------
    def save(self, path, training=True):
        from ..framework.io import save as fsave
        fsave(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None and \
                hasattr(self._optimizer, "state_dict"):
            fsave(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        import os
        from ..framework.io import load as fload
        self.network.set_state_dict(fload(path + ".pdparams"))
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(opt_path) and \
                hasattr(self._optimizer, "set_state_dict"):
            self._optimizer.set_state_dict(fload(opt_path))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        n_params = sum(int(p.numel()) for p in self.network.parameters())
        trainable = sum(int(p.numel()) for p in self.network.parameters()
                        if not p.stop_gradient)
        lines = [f"{type(self.network).__name__}: {n_params:,} params "
                 f"({trainable:,} trainable)"]
        for name, layer in self.network.named_sublayers():
            own = sum(int(p.numel())
                      for p in layer._parameters.values()) if hasattr(
                layer, "_parameters") else 0
            if own:
                lines.append(f"  {name} ({type(layer).__name__}): {own:,}")
        text = "\n".join(lines)
        print(text)
        return {"total_params": n_params, "trainable_params": trainable}
