"""High-level API (parity: python/paddle/hapi/)."""
from . import callbacks  # noqa: F401
from .callbacks import (  # noqa: F401
    Callback, EarlyStopping, LRScheduler, ModelCheckpoint, ProgBarLogger,
    ReduceLROnPlateau, VisualDL, WandbCallback,
)
from .model import Model  # noqa: F401
from .dynamic_flops import flops  # noqa: F401


def summary(net, input_size=None, dtypes=None):
    """Parity: paddle.summary."""
    return Model(net).summary(input_size, dtypes)
