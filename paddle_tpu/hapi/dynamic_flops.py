"""FLOPs counting: ``paddle.flops``.

Parity: python/paddle/hapi/dynamic_flops.py:40 (``flops``) and
static_flops.py. TPU-native design: instead of the reference's per-layer
formula table (which silently counts 0 for any layer class not in the
table), the total is computed by walking the traced jaxpr and pricing
each primitive — every op in any layer, custom or builtin, is covered by
construction. ``print_detail`` re-traces each leaf sublayer with the
input shapes recorded during one eager forward to attribute the total;
``custom_ops`` overrides the count for specific Layer classes.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple, Type

import numpy as np

__all__ = ["flops"]

# primitives priced at one flop per output element
_ELEMENTWISE = {
    "add", "sub", "mul", "div", "rem", "pow", "max", "min", "and", "or",
    "xor", "not", "neg", "sign", "floor", "ceil", "round", "abs", "sqrt",
    "rsqrt", "cbrt", "exp", "exp2", "expm1", "log", "log2", "log1p", "tanh",
    "sin", "cos", "tan", "asin", "acos", "atan", "sinh", "cosh", "asinh",
    "acosh", "atanh", "atan2", "logistic", "erf", "erfc", "erf_inv",
    "is_finite", "nextafter", "square", "reciprocal", "clamp", "select_n",
    "integer_pow", "add_any", "lgamma", "digamma", "polygamma", "igamma",
    "igammac", "regularized_incomplete_beta",
    "eq", "ne", "ge", "gt", "le", "lt", "shift_left",
    "shift_right_logical", "shift_right_arithmetic",
}
# reductions priced at one flop per *input* element
_REDUCTIONS = {
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod", "reduce_and",
    "reduce_or", "reduce_xor", "argmax", "argmin", "reduce_precision",
    "cumsum", "cumprod", "cummax", "cummin", "cumlogsumexp",
}
# pure data movement / metadata — zero flops
_FREE = {
    "reshape", "transpose", "broadcast_in_dim", "slice", "dynamic_slice",
    "dynamic_update_slice", "concatenate", "rev", "pad", "squeeze",
    "convert_element_type", "bitcast_convert_type", "copy", "device_put",
    "gather", "scatter", "iota", "stop_gradient", "real", "imag", "complex",
    "conj", "split", "expand_dims", "sharding_constraint", "pjit_sharding",
}


def _nelems(aval) -> int:
    n = 1
    for d in getattr(aval, "shape", ()):
        n *= int(d)
    return n


def _dot_general_flops(eqn) -> int:
    (lc, rc), (lb, _rb) = eqn.params["dimension_numbers"]
    lhs, rhs = (v.aval.shape for v in eqn.invars[:2])
    batch = 1
    for d in lb:
        batch *= int(lhs[d])
    contract = 1
    for d in lc:
        contract *= int(lhs[d])
    m = 1
    for i, d in enumerate(lhs):
        if i not in lc and i not in lb:
            m *= int(d)
    n = 1
    rb_set = set(_rb)
    for i, d in enumerate(rhs):
        if i not in rc and i not in rb_set:
            n *= int(d)
    return 2 * batch * m * n * contract


def _conv_flops(eqn) -> int:
    out = eqn.outvars[0].aval
    rhs = eqn.invars[1].aval.shape
    dn = eqn.params["dimension_numbers"]
    out_ch = int(rhs[dn.rhs_spec[0]])
    k_elems = 1
    for d in rhs:
        k_elems *= int(d)
    # per output element: one MAC per (kernel spatial tap x in-ch/group)
    return 2 * _nelems(out) * (k_elems // max(out_ch, 1))


def _eqn_flops(eqn) -> int:
    name = eqn.primitive.name
    if name == "dot_general":
        return _dot_general_flops(eqn)
    if name == "conv_general_dilated":
        return _conv_flops(eqn)
    if name in _ELEMENTWISE:
        return _nelems(eqn.outvars[0].aval)
    if name in _REDUCTIONS:
        return _nelems(eqn.invars[0].aval)
    if name in ("sort", "top_k", "approx_top_k"):
        n = _nelems(eqn.invars[0].aval)
        return n * max(int(np.log2(max(n, 2))), 1)
    return 0


def _sub_jaxprs(params) -> List[Tuple[object, int]]:
    """(jaxpr, multiplier) pairs hiding in a higher-order eqn's params."""
    out = []
    for k, v in params.items():
        mult = 1
        if k == "jaxpr" and "length" in params:       # scan body
            mult = int(params["length"])
        vals = v if isinstance(v, (tuple, list)) else [v]
        for item in vals:
            jx = getattr(item, "jaxpr", item)
            if hasattr(jx, "eqns"):
                out.append((jx, mult))
    return out


def _jaxpr_flops(jaxpr) -> int:
    total = 0
    for eqn in jaxpr.eqns:
        subs = _sub_jaxprs(eqn.params)
        if subs:
            if eqn.primitive.name == "cond":
                # branches are alternatives: price the most expensive one
                total += max(_jaxpr_flops(j) for j, _ in subs)
            else:
                total += sum(m * _jaxpr_flops(j) for j, m in subs)
        else:
            total += _eqn_flops(eqn)
    return total


def _trace_layer_flops(layer, in_avals) -> int:
    import jax

    from ..jit import _layer_trace_fn
    pure, state, names, restore = _layer_trace_fn(layer)
    try:
        state_avals = [jax.ShapeDtypeStruct(state[n]._data.shape,
                                            state[n]._data.dtype)
                       for n in names]
        closed = jax.make_jaxpr(pure)(state_avals, *in_avals)
    finally:
        restore()
    return _jaxpr_flops(closed.jaxpr)


def _input_avals(input_size, dtypes):
    import jax
    if input_size is None:
        raise ValueError("flops(net, input_size): input_size is required "
                         "for a Layer")
    sizes: List[Sequence[int]]
    if isinstance(input_size, (list, tuple)) and input_size and \
            isinstance(input_size[0], (list, tuple)):
        sizes = [tuple(s) for s in input_size]
    else:
        sizes = [tuple(input_size)]
    if dtypes is None:
        dts = ["float32"] * len(sizes)
    elif isinstance(dtypes, str):
        dts = [dtypes] * len(sizes)
    else:
        dts = list(dtypes)
    return [jax.ShapeDtypeStruct(tuple(int(d) for d in s), np.dtype(dt))
            for s, dt in zip(sizes, dts)]


def _leaf_records(net, avals, only_classes=None):
    """One eager forward on zeros; record per-leaf input avals via hooks.
    `only_classes` restricts hooking to matching layers (custom_ops
    without print_detail needs records for just those classes)."""
    import jax
    import jax.numpy as jnp

    from ..tensor import Tensor
    records: List[Tuple[str, object, List[object]]] = []
    handles = []
    for name, layer in net.named_sublayers():
        if layer.sublayers():
            continue
        if only_classes is not None and \
                not isinstance(layer, tuple(only_classes)):
            continue

        def hook(lyr, inputs, _name=name):
            ins = [jax.ShapeDtypeStruct(t._data.shape, t._data.dtype)
                   for t in inputs if hasattr(t, "_data")]
            records.append((_name, lyr, ins))

        handles.append(layer.register_forward_pre_hook(hook))
    was_training = net.training
    net.eval()
    try:
        net(*[Tensor(jnp.zeros(a.shape, a.dtype)) for a in avals])
    finally:
        if was_training:
            net.train()
        for h in handles:
            h.remove()
    return records


def flops(net, input_size=None, custom_ops: Optional[Dict[Type, Callable]]
          = None, print_detail: bool = False, dtypes=None) -> int:
    """Count the forward FLOPs of ``net`` at ``input_size``.

    ``net`` may be a ``nn.Layer`` (traced at ``input_size``) or a
    ``static.Program`` (every recorded graph node is priced; ``input_size``
    is ignored, matching the reference's static_flops path). ``custom_ops``
    maps Layer classes to ``fn(layer, input_avals) -> int`` overrides; the
    override replaces the traced count for every call of that layer class.
    ``dtypes`` (a str or per-input list, default float32) sets the traced
    input dtypes — pass "int64" for token-id models. A multiply-accumulate
    counts as 2 FLOPs throughout.
    """
    from ..nn.layer.layers import Layer
    from ..static import Program

    if isinstance(net, Program):
        import warnings

        import jax
        total = 0
        skipped = []
        nodes = [r() for r in getattr(net, "_nodes", [])]
        for node in nodes:
            if node is None:
                continue
            avals = [jax.ShapeDtypeStruct(t._data.shape, t._data.dtype)
                     if not isinstance(t._data, jax.ShapeDtypeStruct)
                     else t._data for t in node.inputs]
            try:
                closed = jax.make_jaxpr(node.fwd)(*avals)
            except Exception as e:  # noqa: BLE001
                skipped.append((node.name, str(e)))
                continue
            total += _jaxpr_flops(closed.jaxpr)
        if skipped:
            warnings.warn(
                f"flops(Program): {len(skipped)} node(s) failed to "
                f"re-trace and are NOT counted: "
                f"{[n for n, _ in skipped[:5]]}; total is a lower bound")
        if print_detail:
            print(f"Total Flops: {total}")
        return int(total)

    if not isinstance(net, Layer):
        raise TypeError(f"flops expects a Layer or static.Program, got "
                        f"{type(net).__name__}")
    avals = _input_avals(input_size, dtypes)
    total = _trace_layer_flops(net, avals)

    if not (print_detail or custom_ops):
        return int(total)

    only = None if print_detail else list(custom_ops)
    records = _leaf_records(net, avals, only_classes=only)
    rows = []
    for name, layer, ins in records:
        ov = None
        if custom_ops:
            for cls, fn in custom_ops.items():
                if isinstance(layer, cls):
                    ov = fn
                    break
        # the standalone re-trace only matters as the subtraction baseline
        # for an override, or as the detail-row value
        need_traced = ov is not None or print_detail
        traced = None
        blind = None  # why the override has no subtraction baseline
        if need_traced and ins:
            try:
                traced = _trace_layer_flops(layer, ins)
            except Exception as e:  # noqa: BLE001
                traced = None
                blind = f"could not re-trace standalone ({e})"
        elif need_traced:
            blind = "recorded no tensor inputs"
        if blind is not None and ov is not None:
            import warnings
            warnings.warn(
                f"flops: leaf {name!r} {blind}; its custom_ops override "
                "is ADDED to the total instead of replacing the traced "
                "contribution — the total may double-count this layer")
        if ov is not None:
            val = int(ov(layer, ins))
            total += val - (traced or 0)  # replace traced contribution
        else:
            val = traced or 0
        n_params = sum(int(np.prod(p.shape)) for p in layer.parameters())
        rows.append((name, type(layer).__name__,
                     [tuple(a.shape) for a in ins], n_params, val))

    if print_detail:
        w = max([len(r[0]) for r in rows] + [10])
        print(f"{'Layer':<{w}}  {'Type':<18} {'Params':>12} {'FLOPs':>16}")
        for name, tname, shapes, n_params, val in rows:
            print(f"{name:<{w}}  {tname:<18} {n_params:>12,} {val:>16,}")
        attributed = sum(r[4] for r in rows)
        print(f"Total Flops: {int(total):,}  "
              f"(leaf-attributed: {attributed:,}; the rest is inter-layer "
              f"glue)  Total Params: "
              f"{sum(int(np.prod(p.shape)) for p in net.parameters()):,}")
    return int(total)
