"""Lint-rule registry: the framework's machine-checkable invariants.

Each rule is a small AST checker with a stable id, a severity, a one-line
description, and a fix hint the CI driver prints next to every finding.
The registry is data the rest of the subsystem consumes: ``astlint`` runs
the checkers, ``tools/lint.py --fix-hints`` prints the remediation table,
and the test suite asserts every rule fires on its fixture snippet.

Rules read their ground truth statically from the modules that own it —
the chaos probe-site registry from ``resilience/chaos.py`` (``SITES``) and
the metric-name catalog from ``profiler/instrument.py`` (``CATALOG``) are
parsed out of the source with ``ast.literal_eval``, so linting never
imports the framework (or JAX): ``tools/lint.py`` stays fast and can lint
a broken tree.

Suppression: append ``# tpu-lint: disable=TPU101`` (comma-separate for
several ids) to the offending line. Suppressions are *checked*: an unknown
rule id in a disable comment is itself a finding (TPU000).
"""
from __future__ import annotations

import ast
import functools
import os
from dataclasses import dataclass
from typing import Callable, Dict, Iterable, List, Optional, Tuple

__all__ = ["Finding", "Rule", "RULES", "rule_table", "get_rule",
           "load_metric_catalog", "load_chaos_sites",
           "load_flag_registry"]

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@dataclass
class Finding:
    """One lint finding, stable enough to diff against a baseline."""
    rule: str
    path: str
    line: int
    col: int
    message: str
    hint: str = ""
    severity: str = "error"  # error | warning

    def key(self) -> str:
        """Baseline identity: rule + file + message (line numbers drift
        with unrelated edits, so they are not part of the key). The file
        part keeps the last two path components so same-named files
        (every __init__.py) do not collide in the baseline."""
        tail = "/".join(self.path.replace(os.sep, "/").split("/")[-2:])
        return f"{self.rule}|{tail}|{self.message}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"[{self.severity}] {self.message}")


@dataclass
class Rule:
    id: str
    name: str
    description: str
    hint: str
    check: Callable  # check(ctx) -> Iterable[Finding]
    severity: str = "error"
    framework_only: bool = False      # skip for user scripts outside the pkg
    exempt_suffixes: Tuple[str, ...] = ()  # path suffixes the rule skips


RULES: Dict[str, Rule] = {}


def _register(id, name, description, hint, severity="error",
              framework_only=False, exempt_suffixes=()):
    def deco(fn):
        RULES[id] = Rule(id, name, description, hint, fn, severity,
                         framework_only, tuple(exempt_suffixes))
        return fn
    return deco


def get_rule(rule_id: str) -> Optional[Rule]:
    return RULES.get(rule_id)


def rule_table() -> List[Tuple[str, str, str, str, str]]:
    """(id, name, severity, description, hint) rows, id-sorted."""
    return [(r.id, r.name, r.severity, r.description, r.hint)
            for r in sorted(RULES.values(), key=lambda r: r.id)]


# -- static ground-truth readers ----------------------------------------------
def _literal_from_source(path: str, target: str):
    """ast.literal_eval of a top-level ``target = <literal>`` assignment."""
    with open(path, "r") as f:
        tree = ast.parse(f.read(), filename=path)
    for node in tree.body:
        if isinstance(node, ast.Assign):
            names = [t.id for t in node.targets if isinstance(t, ast.Name)]
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target,
                                                            ast.Name):
            names = [node.target.id]
        else:
            continue
        if target in names and node.value is not None:
            return ast.literal_eval(node.value)
    raise LookupError(f"no literal assignment {target!r} in {path}")


@functools.lru_cache(maxsize=1)
def load_metric_catalog() -> frozenset:
    """The built-in metric names, read statically from
    profiler/instrument.py's CATALOG tuple."""
    path = os.path.join(_PKG_ROOT, "profiler", "instrument.py")
    return frozenset(_literal_from_source(path, "CATALOG"))


@functools.lru_cache(maxsize=1)
def load_flag_registry() -> frozenset:
    """Every flag name the package defines, read statically from
    ``define_flag("<name>", ...)`` call sites across paddle_tpu/*.py.
    Static on purpose: kernel modules register their flags on first
    import, so a runtime ``flags._FLAGS`` snapshot taken under the
    jax-free bootstrap would miss them — and the perf-config provenance
    check (tools/lint.py --perf-config) must see the full registry."""
    names = set()
    for dirpath, dirnames, filenames in os.walk(_PKG_ROOT):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            try:
                with open(path, "r") as f:
                    tree = ast.parse(f.read())
            except (OSError, SyntaxError):
                continue
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                callee = func.attr if isinstance(func, ast.Attribute) \
                    else getattr(func, "id", None)
                if callee == "define_flag" and node.args and \
                        isinstance(node.args[0], ast.Constant) and \
                        isinstance(node.args[0].value, str):
                    names.add(node.args[0].value)
    return frozenset(names)


@functools.lru_cache(maxsize=1)
def _chaos_sites_cached() -> Tuple[Tuple[str, str], ...]:
    path = os.path.join(_PKG_ROOT, "resilience", "chaos.py")
    return tuple(sorted(_literal_from_source(path, "SITES").items()))


def load_chaos_sites() -> Dict[str, str]:
    """{site name: probe kind}, read statically from
    resilience/chaos.py's SITES registry."""
    return dict(_chaos_sites_cached())


# -- per-file context shared by all checkers ----------------------------------
class FileContext:
    """Parsed file + the name-resolution maps the checkers share.

    ``dotted(node)`` resolves an ast.Name/Attribute chain to a fully
    qualified dotted path using the file's imports, e.g. with
    ``from jax import lax`` the expression ``lax.axis_size`` resolves to
    ``jax.lax.axis_size``; with ``from ..utils.jax_compat import shard_map``
    the name ``shard_map`` resolves to ``<...>.jax_compat.shard_map`` —
    blessed, because it reaches the shim.
    """

    def __init__(self, path: str, source: str, tree: ast.Module,
                 is_framework: bool):
        self.path = path
        self.source = source
        self.tree = tree
        self.is_framework = is_framework
        self.imports: Dict[str, str] = {}
        # one full walk, shared by every rule (the dominant lint cost)
        self._nodes: List[ast.AST] = list(ast.walk(tree))
        self._collect_imports()
        self._functions: Optional[List[ast.AST]] = None
        self._probe_map: Optional[Dict] = None
        self._det_regions: Optional[List] = None

    def nodes(self) -> List[ast.AST]:
        return self._nodes

    def _collect_imports(self):
        for node in self._nodes:
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.imports[a.asname or a.name.split(".")[0]] = \
                        a.name if a.asname else a.name.split(".")[0]
                    if a.asname:
                        self.imports[a.asname] = a.name
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if node.level:  # relative import: keep the module tail
                    mod = ("." * node.level) + mod
                for a in node.names:
                    self.imports[a.asname or a.name] = \
                        f"{mod}.{a.name}" if mod else a.name

    def dotted(self, node) -> Optional[str]:
        """Fully qualified dotted name for a Name/Attribute chain."""
        parts = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        head = self.imports.get(node.id, node.id)
        parts.append(head)
        return ".".join(reversed(parts))

    def functions(self) -> List[ast.AST]:
        if self._functions is None:
            self._functions = [n for n in self._nodes
                               if isinstance(n, (ast.FunctionDef,
                                                 ast.AsyncFunctionDef))]
        return self._functions


def _finding(rule: Rule, ctx: FileContext, node, message: str) -> Finding:
    return Finding(rule.id, ctx.path, getattr(node, "lineno", 0),
                   getattr(node, "col_offset", 0), message, rule.hint,
                   rule.severity)


def _calls_in(node) -> Iterable[ast.Call]:
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            yield n


def _own_body_walk(fn) -> Iterable[ast.AST]:
    """Walk a function's body WITHOUT descending into nested function
    definitions (each nested def is its own region for region rules)."""
    stack = list(fn.body)
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        stack.extend(ast.iter_child_nodes(n))


# =============================================================================
# TPU1xx — version-shim invariants (the PR-2 bug class)
# =============================================================================
_RAW_SHARD_MAP = {"jax.shard_map", "jax.experimental.shard_map.shard_map"}
_RAW_AXIS_SIZE = {"jax.lax.axis_size", "lax.axis_size"}
_RAW_COMPILER_PARAMS_TAILS = ("pallas.tpu.CompilerParams",
                              "pallas.tpu.TPUCompilerParams")


def _is_compat(name: Optional[str]) -> bool:
    return bool(name) and ".jax_compat." in f".{name}"


@_register(
    "TPU101", "raw-shard-map",
    "raw jax.shard_map / jax.experimental.shard_map call site outside "
    "utils/jax_compat.py",
    "import shard_map from paddle_tpu.utils.jax_compat — the shim accepts "
    "the current-JAX kwargs everywhere and translates on 0.4.x, where the "
    "raw spelling does not exist (this exact bypass caused PR 2's 32 "
    "tier-1 failures)",
    exempt_suffixes=("utils/jax_compat.py",))
def _check_raw_shard_map(ctx: FileContext):
    rule = RULES["TPU101"]
    for node in ctx.nodes():
        if isinstance(node, (ast.Attribute, ast.Name)):
            d = ctx.dotted(node)
            if d in _RAW_SHARD_MAP and not _is_compat(d):
                yield _finding(rule, ctx, node,
                               f"raw shard_map reference ({d})")
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod == "jax.experimental.shard_map" or (
                    mod == "jax" and any(a.name == "shard_map"
                                         for a in node.names)):
                yield _finding(rule, ctx, node,
                               f"raw shard_map import (from {mod})")


@_register(
    "TPU102", "raw-axis-size",
    "raw jax.lax.axis_size call site outside utils/jax_compat.py",
    "import axis_size from paddle_tpu.utils.jax_compat — on pre-promotion "
    "JAX the symbol does not exist and the shim emulates it with a psum "
    "of 1",
    exempt_suffixes=("utils/jax_compat.py",))
def _check_raw_axis_size(ctx: FileContext):
    rule = RULES["TPU102"]
    for node in ctx.nodes():
        if isinstance(node, ast.Attribute):
            d = ctx.dotted(node)
            if d in _RAW_AXIS_SIZE and not _is_compat(d):
                yield _finding(rule, ctx, node,
                               f"raw axis_size reference ({d})")
        elif isinstance(node, ast.ImportFrom):
            if (node.module or "") == "jax.lax" and any(
                    a.name == "axis_size" for a in node.names):
                yield _finding(rule, ctx, node,
                               "raw axis_size import (from jax.lax)")


@_register(
    "TPU103", "raw-compiler-params",
    "Pallas CompilerParams/TPUCompilerParams constructed outside "
    "utils/jax_compat.py",
    "call paddle_tpu.utils.jax_compat.tpu_compiler_params(**kw) — the "
    "class was renamed when Pallas-TPU stabilized, so the raw spelling "
    "only exists on one side of the version boundary",
    exempt_suffixes=("utils/jax_compat.py",))
def _check_raw_compiler_params(ctx: FileContext):
    rule = RULES["TPU103"]
    for node in ctx.nodes():
        if not isinstance(node, ast.Call):
            continue
        d = ctx.dotted(node.func)
        if not d or _is_compat(d):
            continue
        if d.endswith(_RAW_COMPILER_PARAMS_TAILS) or \
                d.endswith(("pltpu.CompilerParams",
                            "pltpu.TPUCompilerParams")):
            yield _finding(rule, ctx, node,
                           f"raw Pallas compiler-params construction ({d})")


# =============================================================================
# TPU2xx — determinism at chaos-probe sites / traced regions
# =============================================================================
_PROBE_FNS = {"site", "mangle", "poison"}
_WALLCLOCK = {"time.time", "time.time_ns", "datetime.now",
              "datetime.datetime.now", "datetime.utcnow",
              "datetime.datetime.utcnow"}
_JIT_DECORATORS = {"jax.jit", "jit", "jax.pjit", "pjit", "to_static",
                   "jit.to_static", "paddle.jit.to_static",
                   "functools.partial(jax.jit"}


def _probe_calls_uncached(ctx: FileContext, fn) -> List[ast.Call]:
    """chaos probe calls (site/mangle/poison on a chaos-ish module, or the
    bare names imported from resilience.chaos) in fn's OWN body."""
    out = []
    for n in _own_body_walk(fn):
        for c in (x for x in [n] if isinstance(x, ast.Call)):
            d = ctx.dotted(c.func)
            if not d:
                continue
            head, _, tail = d.rpartition(".")
            if tail in _PROBE_FNS and ("chaos" in head or
                                       head.endswith("_chaos")):
                out.append(c)
            elif not head and d in _PROBE_FNS and \
                    "chaos" in ctx.imports.get(d, ""):
                out.append(c)
    return out


def _probe_map(ctx: FileContext) -> Dict:
    """{function node: [probe Call nodes]} — computed once per file;
    cheap pre-filter: files never naming 'chaos' have no probes."""
    if ctx._probe_map is None:
        if "chaos" not in ctx.source:
            ctx._probe_map = {}
        else:
            ctx._probe_map = {
                fn: calls for fn in ctx.functions()
                if (calls := _probe_calls_uncached(ctx, fn))}
    return ctx._probe_map


def _probe_calls(ctx: FileContext, fn) -> List[ast.Call]:
    return _probe_map(ctx).get(fn, [])


def _is_jitted(ctx: FileContext, fn) -> bool:
    for dec in getattr(fn, "decorator_list", ()):
        target = dec.func if isinstance(dec, ast.Call) else dec
        d = ctx.dotted(target)
        if d and (d in _JIT_DECORATORS or d.endswith(".jit") or
                  d.endswith("to_static")):
            return True
    return False


def _region_label(ctx, fn):
    return ("jit-traced" if _is_jitted(ctx, fn) else "chaos-probed")


def _deterministic_regions(ctx: FileContext):
    if ctx._det_regions is None:
        probed = _probe_map(ctx)
        ctx._det_regions = [fn for fn in ctx.functions()
                            if fn in probed or _is_jitted(ctx, fn)]
    return ctx._det_regions


@_register(
    "TPU201", "wallclock-at-probe-site",
    "non-monotonic wall-clock read (time.time / datetime.now) inside a "
    "chaos-probed or jit-traced region",
    "use time.monotonic()/time.perf_counter() for deadlines and "
    "durations — wall clocks jump (NTP, suspend) and break the seeded "
    "chaos replay contract; inside jit the read executes once at trace "
    "time and bakes a stale constant",
    framework_only=True, exempt_suffixes=("resilience/chaos.py",))
def _check_wallclock(ctx: FileContext):
    rule = RULES["TPU201"]
    for fn in _deterministic_regions(ctx):
        for n in _own_body_walk(fn):
            if isinstance(n, ast.Call):
                d = ctx.dotted(n.func)
                if d in _WALLCLOCK:
                    yield _finding(
                        rule, ctx, n,
                        f"{d}() in {_region_label(ctx, fn)} function "
                        f"'{fn.name}'")


@_register(
    "TPU202", "unseeded-random-at-probe-site",
    "global (unseeded) random.* call inside a chaos-probed or jit-traced "
    "region",
    "use a seeded random.Random(seed) instance (the chaos FaultPlan "
    "carries one: plan.rng()) so the same seed replays the same run; "
    "inside jit use jax.random with an explicit key",
    framework_only=True, exempt_suffixes=("resilience/chaos.py",))
def _check_unseeded_random(ctx: FileContext):
    rule = RULES["TPU202"]
    for fn in _deterministic_regions(ctx):
        for n in _own_body_walk(fn):
            if isinstance(n, ast.Call):
                d = ctx.dotted(n.func)
                if d and d.startswith("random.") and d != "random.Random":
                    yield _finding(
                        rule, ctx, n,
                        f"{d}() in {_region_label(ctx, fn)} function "
                        f"'{fn.name}'")


@_register(
    "TPU203", "unknown-chaos-site",
    "chaos probe called with a site name absent from resilience.chaos.SITES "
    "(or with the wrong probe function for that site)",
    "add the site to the SITES registry in resilience/chaos.py (one source "
    "of truth: linter, install_plan validation, and docs all read it)",
    framework_only=True, exempt_suffixes=("resilience/chaos.py",))
def _check_chaos_sites(ctx: FileContext):
    rule = RULES["TPU203"]
    try:
        sites = load_chaos_sites()
    except (OSError, LookupError):
        return
    for fn, calls in _probe_map(ctx).items():
        for call in calls:
            if not call.args or not isinstance(call.args[0], ast.Constant) \
                    or not isinstance(call.args[0].value, str):
                continue  # dynamic site names pass through (store._run)
            name = call.args[0].value
            probe = ctx.dotted(call.func).rpartition(".")[2]
            if name not in sites:
                yield _finding(rule, ctx, call,
                               f"probe site {name!r} not in chaos.SITES")
            elif sites[name] != probe:
                yield _finding(
                    rule, ctx, call,
                    f"site {name!r} is registered for probe "
                    f"'{sites[name]}' but called via '{probe}'")


# =============================================================================
# TPU3xx — observability-plane invariants
# =============================================================================
_METRIC_METHODS = {"counter", "gauge", "histogram"}


@_register(
    "TPU301", "uncataloged-metric",
    "metric family created with a literal name absent from "
    "profiler/instrument.py's CATALOG",
    "add the family name to instrument.CATALOG (and the module docstring "
    "table) — the catalog is the stable, greppable metric API dashboards "
    "depend on",
    framework_only=True,
    exempt_suffixes=("profiler/metrics.py",))
def _check_metric_catalog(ctx: FileContext):
    rule = RULES["TPU301"]
    try:
        catalog = load_metric_catalog()
    except (OSError, LookupError):
        return
    import fnmatch as _fn
    for node in ctx.nodes():
        if not (isinstance(node, ast.Call) and
                isinstance(node.func, ast.Attribute) and
                node.func.attr in _METRIC_METHODS and node.args):
            continue
        first = node.args[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            name = first.value
            if name not in catalog:
                yield _finding(rule, ctx, node,
                               f"metric {name!r} not in instrument.CATALOG")
        elif isinstance(first, ast.JoinedStr):
            # f-string name: wildcard the formatted fields and require the
            # pattern to cover at least one cataloged family
            pat = "".join(
                v.value if isinstance(v, ast.Constant) else "*"
                for v in first.values)
            if not any(_fn.fnmatchcase(c, pat) for c in catalog):
                yield _finding(
                    rule, ctx, node,
                    f"metric f-string pattern {pat!r} matches nothing in "
                    "instrument.CATALOG")


# =============================================================================
# TPU4xx — exception hygiene around checkpoint integrity
# =============================================================================
_CKPT_LOADS = {"load_state_dict", "load_latest"}
_BROAD = {"Exception", "BaseException", "ValueError"}


def _handler_reraises(handler: ast.ExceptHandler) -> bool:
    for n in ast.walk(handler):
        if isinstance(n, ast.Raise):
            return True
    return False


@_register(
    "TPU401", "bare-except",
    "bare 'except:' swallows everything, including KeyboardInterrupt and "
    "CheckpointCorruptionError",
    "name the exception types you can actually handle (at minimum "
    "'except Exception'); let corruption and interrupts propagate")
def _check_bare_except(ctx: FileContext):
    rule = RULES["TPU401"]
    for node in ctx.nodes():
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            yield _finding(rule, ctx, node, "bare 'except:' handler")


@_register(
    "TPU402", "swallowed-ckpt-error",
    "broad except around a checkpoint load can swallow "
    "CheckpointCorruptionError (a ValueError subclass) and train from "
    "garbage",
    "catch CheckpointCorruptionError explicitly first (fall back via "
    "resilience.CheckpointManager.load_latest), or re-raise it from the "
    "broad handler")
def _check_swallowed_ckpt(ctx: FileContext):
    rule = RULES["TPU402"]
    for node in ctx.nodes():
        if not isinstance(node, ast.Try):
            continue
        loads = [c for stmt in node.body for c in _calls_in(stmt)
                 if (d := ctx.dotted(c.func)) and
                 d.rpartition(".")[2] in _CKPT_LOADS]
        if not loads:
            continue
        for h in node.handlers:
            names = []
            if h.type is None:
                names = ["<bare>"]
            else:
                types = h.type.elts if isinstance(h.type, ast.Tuple) \
                    else [h.type]
                names = [t.rpartition(".")[2] for n in types
                         if (t := (ctx.dotted(n) or ""))]
            caught = [n for n in names if n in _BROAD or n == "<bare>"]
            if caught and not _handler_reraises(h):
                yield _finding(
                    rule, ctx, h,
                    f"'except {', '.join(caught)}' around "
                    f"{loads[0].func.attr if isinstance(loads[0].func, ast.Attribute) else ctx.dotted(loads[0].func)}"
                    "() does not re-raise")


# =============================================================================
# TPU5xx — layer-construction hygiene
# =============================================================================
_MUTABLE_LITERALS = (ast.List, ast.Dict, ast.Set, ast.ListComp,
                     ast.DictComp, ast.SetComp)
_MUTABLE_CALLS = {"list", "dict", "set", "bytearray",
                  "collections.defaultdict", "collections.OrderedDict"}


@_register(
    "TPU501", "mutable-default-arg",
    "mutable default argument in a class constructor: every instance "
    "shares ONE object, so layer state bleeds across instances",
    "default to None and materialize inside __init__ "
    "(x = [] if x is None else x)",
    framework_only=True)
def _check_mutable_defaults(ctx: FileContext):
    rule = RULES["TPU501"]
    for node in ctx.nodes():
        if not isinstance(node, ast.ClassDef):
            continue
        for item in node.body:
            if not (isinstance(item, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)) and
                    item.name == "__init__"):
                continue
            defaults = list(item.args.defaults) + \
                [d for d in item.args.kw_defaults if d is not None]
            for d in defaults:
                bad = isinstance(d, _MUTABLE_LITERALS) or (
                    isinstance(d, ast.Call) and
                    (ctx.dotted(d.func) or "") in _MUTABLE_CALLS)
                if bad:
                    yield _finding(
                        rule, ctx, d,
                        f"mutable default in {node.name}.__init__")


# SHD1xx (sharding/layout), CCY1xx/2xx (concurrency/lifecycle) and
# WIR1xx (wire-contract) rules register themselves into RULES; the
# imports sit at the bottom so each module can import this module's
# half-initialized namespace (everything they need is defined above).
from . import shard_rules  # noqa: E402,F401
from . import concur_rules  # noqa: E402,F401
from . import wire_rules  # noqa: E402,F401
