"""CCY5xx — concurrency-registry coherence (the concurcheck driver half).

The static rules (CCY101..CCY201 in ``analysis/concur_rules.py``) and
the runtime twin (``serving/locking.OrderedLock``, armed via
``PADDLE_LOCKCHECK``) both take their ground truth from two literal
registries:

* ``serving/locking.py`` — LOCK_ORDER / LOCK_OWNERS / LOCK_BEARERS /
  LOCK_CORE_MODULES
* ``serving/scheduler.py`` — REQUEST_TRANSITIONS

A linter whose registry is self-contradictory lies politely: it keeps
exiting 0 while enforcing nothing. This module is the fourth lint
pass's self-check — it proves the registries are internally coherent
and that the runtime twin sees exactly the same order the static rules
enforce, so the two halves cannot drift apart:

* **CCY510** — lock-registry incoherence: duplicate names in
  LOCK_ORDER, an owner/bearer mapping onto an undeclared lock, or an
  empty/degenerate core-module list.
* **CCY511** — transition-table incoherence: an edge targeting an
  undeclared state, a missing ``"new"`` birth state, a non-terminal
  ``"finished"``, or a state unreachable from ``"new"``.
* **CCY520** — static/runtime drift: the registry the runtime
  ``locking`` module actually exposes differs from the one the static
  rules parsed, or OrderedLock cannot rank a declared lock name.

Stdlib-only: the runtime ``locking`` module is loaded BY FILE PATH
(``importlib.util.spec_from_file_location``), never through the
``paddle_tpu.serving`` package — importing that package pulls the
engine and therefore jax, which the lint driver must not need.
"""
from __future__ import annotations

import functools
import importlib.util
import os
from typing import List

from .concur_rules import (load_lock_bearers, load_lock_core_modules,
                           load_lock_order, load_lock_owners,
                           load_request_transitions)
from .rules import Finding, _PKG_ROOT

__all__ = ["CONCUR_RULES", "concur_check", "load_locking_module"]

CONCUR_RULES = {
    "CCY510": ("lock-registry-incoherent",
               "serving/locking.py's LOCK_ORDER must list each lock "
               "once, every LOCK_OWNERS/LOCK_BEARERS value must name a "
               "declared lock, and LOCK_CORE_MODULES must be .py "
               "basenames — an incoherent registry makes CCY101 and "
               "the OrderedLock twin silently under-enforce"),
    "CCY511": ("transition-table-incoherent",
               "serving/scheduler.py REQUEST_TRANSITIONS must be closed "
               "(every edge target is a declared state), born from "
               "'new', terminal at 'finished' (no outgoing edges), and "
               "fully reachable from 'new' — otherwise CCY201 enforces "
               "a lifecycle no request can actually live"),
    "CCY520": ("static-runtime-lock-order-drift",
               "the registry serving/locking.py exposes at runtime must "
               "be byte-identical to the literals the static rules "
               "parse, and OrderedLock must rank every declared name — "
               "drift here means the armed twin and the lint gate "
               "enforce different orders"),
}

_LOCKING_PATH = os.path.join(_PKG_ROOT, "serving", "locking.py")
_SCHED_PATH = os.path.join(_PKG_ROOT, "serving", "scheduler.py")


def _finding(rule: str, path: str, message: str) -> Finding:
    return Finding(rule, path, 0, 0, message, CONCUR_RULES[rule][1])


@functools.lru_cache(maxsize=1)
def load_locking_module():
    """The runtime ``serving.locking`` module, loaded by file path so
    no package __init__ (and hence no jax) runs. Shared by the lint
    driver's CCY520 check, the concur tier-1 tests, and the chaos
    drill's --lockcheck scenario."""
    spec = importlib.util.spec_from_file_location(
        "paddle_tpu_serving_locking_standalone", _LOCKING_PATH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _check_lock_registry(out: List[Finding]) -> None:
    order = load_lock_order()
    if len(set(order)) != len(order) or not order:
        out.append(_finding(
            "CCY510", _LOCKING_PATH,
            f"LOCK_ORDER is empty or repeats a lock name: {order!r}"))
    declared = set(order)
    for what, mapping in (("LOCK_OWNERS", load_lock_owners()),
                          ("LOCK_BEARERS", load_lock_bearers())):
        for key, lock in sorted(mapping.items()):
            if lock not in declared:
                out.append(_finding(
                    "CCY510", _LOCKING_PATH,
                    f"{what}[{key!r}] maps to {lock!r}, which is not in "
                    f"LOCK_ORDER {order!r}"))
    core = load_lock_core_modules()
    if not core or not all(m.endswith(".py") and "/" not in m
                           for m in core):
        out.append(_finding(
            "CCY510", _LOCKING_PATH,
            f"LOCK_CORE_MODULES must be non-empty .py basenames, got "
            f"{core!r}"))


def _check_transition_table(out: List[Finding]) -> None:
    table = load_request_transitions()
    states = set(table)
    for frm, outs in sorted(table.items()):
        for to in outs:
            if to not in states:
                out.append(_finding(
                    "CCY511", _SCHED_PATH,
                    f"edge {frm!r} -> {to!r} targets an undeclared "
                    f"state (declared: {sorted(states)})"))
    if "new" not in states:
        out.append(_finding(
            "CCY511", _SCHED_PATH,
            "no 'new' birth state: __init__ assignments have no edge "
            "to check against"))
    if table.get("finished"):
        out.append(_finding(
            "CCY511", _SCHED_PATH,
            f"'finished' must be terminal but has outgoing edges "
            f"{table['finished']!r}"))
    # every declared state must be reachable from 'new'
    seen, frontier = {"new"}, ["new"]
    while frontier:
        for to in table.get(frontier.pop(), ()):
            if to in states and to not in seen:
                seen.add(to)
                frontier.append(to)
    for orphan in sorted(states - seen):
        out.append(_finding(
            "CCY511", _SCHED_PATH,
            f"state {orphan!r} is unreachable from 'new'"))


def _check_runtime_twin(out: List[Finding]) -> None:
    try:
        mod = load_locking_module()
    except Exception as e:  # pragma: no cover - import is stdlib-only
        out.append(_finding(
            "CCY520", _LOCKING_PATH,
            f"runtime locking module failed to load standalone: {e}"))
        return
    pairs = (("LOCK_ORDER", tuple(load_lock_order())),
             ("LOCK_OWNERS", dict(load_lock_owners())),
             ("LOCK_BEARERS", dict(load_lock_bearers())),
             ("LOCK_CORE_MODULES", tuple(load_lock_core_modules())))
    for name, static in pairs:
        runtime = getattr(mod, name, None)
        if runtime is None or \
                (tuple(runtime) if isinstance(static, tuple)
                 else dict(runtime)) != static:
            out.append(_finding(
                "CCY520", _LOCKING_PATH,
                f"runtime {name} ({runtime!r}) differs from the "
                f"statically parsed literal ({static!r})"))
    for lock_name in load_lock_order():
        try:
            mod.OrderedLock(lock_name)
        except Exception as e:
            out.append(_finding(
                "CCY520", _LOCKING_PATH,
                f"OrderedLock cannot rank declared lock "
                f"{lock_name!r}: {e}"))


def concur_check() -> List[Finding]:
    """The fourth lint pass's self-check: registry coherence + runtime
    twin agreement. Returns CCY5xx findings (empty on a healthy tree);
    tools/lint.py diffs them against tools/concur_baseline.json."""
    out: List[Finding] = []
    _check_lock_registry(out)
    _check_transition_table(out)
    _check_runtime_twin(out)
    return out
