"""WIR5xx — wire-registry coherence (the wirecheck driver half).

The static rules (WIR101..WIR106 in ``analysis/wire_rules.py``) and the
runtime sealing twin (``serving/wire.seal``, armed via
``PADDLE_WIRECHECK``) both take their ground truth from one literal
registry: ``serving/wire.py``'s ``WIRE_SCHEMAS`` (+ the
``NON_WIRE_SINKS`` exemption list). A linter whose registry is
self-contradictory lies politely: it keeps exiting 0 while enforcing
nothing. This module is the fifth lint pass's self-check:

* **WIR510** — schema incoherence: a family whose ``family`` field
  disagrees with its key, overlapping required/optional key sets, a
  version key the schema does not declare, an unparseable type spec,
  item specs without an ``item_key``, or a malformed builder/consumer/
  sink spelling.
* **WIR511** — version-hash mismatch: ``key_hashes`` lacks a pin for
  the current version, or the pinned hash differs from the hash of the
  declared key-set + type specs — a schema edited without a version
  bump (the registry-side half of WIR104).
* **WIR520** — static/runtime drift: the registry the runtime ``wire``
  module actually exposes differs from the literal the static rules
  parsed, its ``key_hash`` disagrees with the static computation, or
  ``validate`` cannot accept a minimal well-formed record.

Stdlib-only: the runtime ``wire`` module is loaded BY FILE PATH
(``importlib.util.spec_from_file_location``), never through the
``paddle_tpu.serving`` package — importing that package pulls the
engine and therefore jax, which the lint driver must not need.
"""
from __future__ import annotations

import functools
import importlib.util
import os
import zlib
from typing import List

from .rules import Finding, _PKG_ROOT
from .wire_rules import load_non_wire_sinks, load_wire_schemas

__all__ = ["WIRE_RULES", "wire_check", "load_wire_module",
           "static_key_hash"]

WIRE_RULES = {
    "WIR510": ("wire-schema-incoherent",
               "serving/wire.py's WIRE_SCHEMAS must be internally "
               "coherent: disjoint required/optional sets, a declared "
               "version key, known type specs, and well-formed "
               "builder/consumer/sink spellings — an incoherent "
               "registry makes WIR101..WIR106 and the seal() twin "
               "silently under-enforce"),
    "WIR511": ("wire-version-hash-mismatch",
               "each family's key_hashes must pin the current version "
               "to the hash of its declared key-set + type specs; a "
               "mismatch means the schema was edited without a version "
               "bump — bump the version and append a fresh pin, never "
               "overwrite an old one"),
    "WIR520": ("static-runtime-wire-drift",
               "the registry serving/wire.py exposes at runtime must be "
               "byte-identical to the literal the static rules parse, "
               "hash with the same key_hash, and validate a minimal "
               "well-formed record — drift here means the armed twin "
               "and the lint gate enforce different contracts"),
}

_WIRE_PATH = os.path.join(_PKG_ROOT, "serving", "wire.py")

# the type-spec vocabulary (kept in sync with wire._type_ok; WIR510
# rejects registry entries these cannot parse)
_BASE_SPECS = {"int", "float", "number", "str", "bool", "none", "dict",
               "list", "json", "device", "prefix_keys", "crc"}


def _spec_ok(spec) -> bool:
    if not isinstance(spec, str) or not spec:
        return False
    for part in spec.split("|"):
        if part in _BASE_SPECS:
            continue
        if part.startswith("list[") and part.endswith("]") \
                and _spec_ok(part[5:-1]):
            continue
        return False
    return True


def _finding(rule: str, message: str) -> Finding:
    return Finding(rule, _WIRE_PATH, 0, 0, message, WIRE_RULES[rule][1])


def static_key_hash(spec: dict) -> str:
    """The schema-evolution pin, computed from the statically parsed
    literal — deliberately reimplemented (not imported from the runtime
    module) so WIR520 can catch the runtime half drifting."""
    basis = repr((spec["version_key"],
                  tuple(sorted(spec["required"].items())),
                  tuple(sorted(spec["optional"].items())),
                  spec.get("item_key"),
                  tuple(sorted(spec.get("item_required", {}).items())),
                  tuple(sorted(spec.get("item_optional", {}).items()))))
    return f"{zlib.crc32(basis.encode('utf-8')) & 0xFFFFFFFF:08x}"


@functools.lru_cache(maxsize=1)
def load_wire_module():
    """The runtime ``serving.wire`` module, loaded by file path so no
    package __init__ (and hence no jax) runs. Shared by the lint
    driver's WIR520 check, the wire tier-1 tests, and the chaos
    drill's --wirecheck scenario."""
    spec = importlib.util.spec_from_file_location(
        "paddle_tpu_serving_wire_standalone", _WIRE_PATH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _minimal_record(spec: dict) -> dict:
    """A smallest well-formed record of the family — what WIR520 feeds
    the runtime validate() to prove the twin accepts its own schema."""
    samples = {"int": 0, "float": 0.0, "number": 0, "str": "x",
               "bool": False, "none": None, "dict": {}, "list": [],
               "json": {}, "device": None, "prefix_keys": [],
               "crc": 0}

    def sample(tspec: str):
        part = tspec.split("|")[0]
        if part.startswith("list["):
            return []
        return samples.get(part)

    rec = {k: sample(t) for k, t in spec["required"].items()}
    rec[spec["version_key"]] = spec["version"]
    return rec


def _check_schema(out: List[Finding]) -> None:
    schemas = load_wire_schemas()
    if not schemas:
        out.append(_finding("WIR510", "WIRE_SCHEMAS is empty"))
        return
    for fam, spec in sorted(schemas.items()):
        if spec.get("family") != fam:
            out.append(_finding(
                "WIR510", f"entry {fam!r} declares family="
                          f"{spec.get('family')!r}"))
        req, opt = spec["required"], spec["optional"]
        overlap = sorted(set(req) & set(opt))
        if overlap:
            out.append(_finding(
                "WIR510", f"{fam}: keys {overlap} are both required "
                          f"and optional"))
        if spec["version_key"] not in req:
            out.append(_finding(
                "WIR510", f"{fam}: version key "
                          f"{spec['version_key']!r} is not a required "
                          f"key"))
        if not isinstance(spec["version"], int) or spec["version"] < 1:
            out.append(_finding(
                "WIR510", f"{fam}: version must be an int >= 1, got "
                          f"{spec['version']!r}"))
        for where, mapping in (("required", req), ("optional", opt),
                               ("item_required", spec["item_required"]),
                               ("item_optional",
                                spec["item_optional"])):
            for key, tspec in sorted(mapping.items()):
                if not _spec_ok(tspec):
                    out.append(_finding(
                        "WIR510", f"{fam}: {where}[{key!r}] has "
                                  f"unknown type spec {tspec!r}"))
        item_overlap = sorted(set(spec["item_required"])
                              & set(spec["item_optional"]))
        if item_overlap:
            out.append(_finding(
                "WIR510", f"{fam}: row keys {item_overlap} are both "
                          f"required and optional"))
        if spec["item_key"] is None and (spec["item_required"]
                                         or spec["item_optional"]):
            out.append(_finding(
                "WIR510", f"{fam}: item specs declared without an "
                          f"item_key"))
        if spec["item_key"] is not None \
                and spec["item_key"] not in req:
            out.append(_finding(
                "WIR510", f"{fam}: item_key {spec['item_key']!r} is "
                          f"not a required key"))
        for what in ("builders", "sinks"):
            for s in spec[what]:
                if not (isinstance(s, str) and s.count("::") == 1
                        and s.split("::")[0].endswith(".py")
                        and s.split("::")[1]):
                    out.append(_finding(
                        "WIR510", f"{fam}: malformed {what} spelling "
                                  f"{s!r} (want 'dir/file.py::func')"))
        for what in ("consumers", "item_consumers"):
            for pair in spec[what]:
                if not (isinstance(pair, tuple) and len(pair) == 2
                        and isinstance(pair[0], str)
                        and pair[0].count("::") == 1
                        and isinstance(pair[1], str) and pair[1]):
                    out.append(_finding(
                        "WIR510", f"{fam}: malformed {what} entry "
                                  f"{pair!r} (want ('dir/file.py::"
                                  f"func', 'var'))"))
    for s in load_non_wire_sinks():
        if not (isinstance(s, str) and s.count("::") == 1):
            out.append(_finding(
                "WIR510", f"malformed NON_WIRE_SINKS spelling {s!r}"))


def _check_version_hashes(out: List[Finding]) -> None:
    for fam, spec in sorted(load_wire_schemas().items()):
        pins = spec["key_hashes"]
        pin = pins.get(spec["version"])
        if pin is None:
            out.append(_finding(
                "WIR511", f"{fam}: key_hashes has no pin for the "
                          f"current version {spec['version']} "
                          f"(pinned: {sorted(pins)})"))
            continue
        want = static_key_hash(spec)
        if pin != want:
            out.append(_finding(
                "WIR511", f"{fam}: key_hashes[{spec['version']}] is "
                          f"{pin!r} but the declared keys hash to "
                          f"{want!r} — schema edited without a "
                          f"version bump"))


def _check_runtime_twin(out: List[Finding]) -> None:
    try:
        mod = load_wire_module()
    except Exception as e:  # pragma: no cover - import is stdlib-only
        out.append(_finding(
            "WIR520", f"runtime wire module failed to load "
                      f"standalone: {e}"))
        return
    static = load_wire_schemas()
    runtime = getattr(mod, "WIRE_SCHEMAS", None)
    if runtime != static:
        drift = sorted(set(static) ^ set(runtime or {})) or \
            sorted(f for f in static if static[f] != (runtime or
                                                      {}).get(f))
        out.append(_finding(
            "WIR520", f"runtime WIRE_SCHEMAS differs from the "
                      f"statically parsed literal (families: "
                      f"{drift})"))
        return
    if tuple(getattr(mod, "NON_WIRE_SINKS", ())) \
            != load_non_wire_sinks():
        out.append(_finding(
            "WIR520", "runtime NON_WIRE_SINKS differs from the "
                      "statically parsed literal"))
    for fam, spec in sorted(static.items()):
        if mod.key_hash(spec) != static_key_hash(spec):
            out.append(_finding(
                "WIR520", f"{fam}: runtime key_hash() disagrees with "
                          f"the static computation"))
        try:
            mod.validate(_minimal_record(spec), fam)
        except Exception as e:
            out.append(_finding(
                "WIR520", f"{fam}: runtime validate() rejects a "
                          f"minimal well-formed record: {e}"))


def wire_check() -> List[Finding]:
    """The fifth lint pass's self-check: registry coherence + version
    pins + runtime twin agreement. Returns WIR5xx findings (empty on a
    healthy tree); tools/lint.py diffs them against
    tools/wire_baseline.json."""
    out: List[Finding] = []
    _check_schema(out)
    _check_version_hashes(out)
    _check_runtime_twin(out)
    return out
