"""Static analysis + trace sanitation: catch TPU sharp bits before a run.

Two complementary passes (driven together by ``tools/lint.py``):

* ``analysis.astlint`` / ``analysis.rules`` — an AST linter for the
  framework's machine-checkable invariants: raw ``jax.shard_map`` /
  ``lax.axis_size`` / Pallas ``CompilerParams`` spellings that bypass the
  ``utils/jax_compat`` version shims (PR 2's 32-failure bug class),
  wall-clock/unseeded-random reads inside chaos-probed or jit-traced
  regions, metric names missing from the ``profiler.instrument`` catalog,
  unknown chaos probe sites, broad excepts that can swallow
  ``CheckpointCorruptionError``, and mutable default args in
  constructors. Rules carry stable ids, severities and fix hints;
  ``# tpu-lint: disable=<ID>`` suppresses per line and is itself checked.
* ``analysis.tracecheck`` — dynamic: traces a step function and flags
  recompile hazards (scalar closures, Python branches on tracers,
  empirical retrace on same-shape inputs), host round-trips inside the
  step, donated buffers no output can reuse, and — with per-rank
  schedules captured by ``analysis.schedule`` — cross-rank collective
  order divergence.

The linter half is stdlib-only; the trace half needs JAX and loads
lazily, so ``import paddle_tpu.analysis`` stays cheap for editors and CI.
"""
from __future__ import annotations

from . import schedule  # noqa: F401  (stdlib-only)
from .astlint import (iter_python_files, lint_file, lint_paths,  # noqa: F401
                      lint_source)
from .rules import (RULES, Finding, get_rule,  # noqa: F401
                    load_chaos_sites, load_metric_catalog, rule_table)

__all__ = [
    "Finding", "RULES", "get_rule", "rule_table",
    "lint_source", "lint_file", "lint_paths", "iter_python_files",
    "load_chaos_sites", "load_metric_catalog",
    "schedule", "trace_check", "check_collective_schedules", "TRACE_RULES",
]

_LAZY = {"trace_check", "check_collective_schedules", "TRACE_RULES"}


def __getattr__(name):  # tracecheck imports jax; defer until first use
    if name in _LAZY or name == "tracecheck":
        # importlib, NOT `from . import ...`: the latter re-enters this
        # __getattr__ through _handle_fromlist and recurses
        import importlib
        mod = importlib.import_module(".tracecheck", __name__)
        return mod if name == "tracecheck" else getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
