"""Static analysis + trace sanitation: catch TPU sharp bits before a run.

Five cooperating passes (driven together by ``tools/lint.py``):

* ``analysis.astlint`` / ``analysis.rules`` / ``analysis.shard_rules``
  / ``analysis.concur_rules`` / ``analysis.wire_rules`` — stdlib-only
  AST linting of the framework's machine-checkable invariants,
  including the sharding/layout surface, the serving tier's
  concurrency + request-lifecycle discipline, and the wire contracts
  of every record that crosses a process/host boundary.
* ``analysis.tracecheck`` — dynamic: traces a step function and flags
  recompile hazards, host syncs, wasted donations, and (with per-rank
  schedules captured by ``analysis.schedule``) cross-rank collective
  order divergence.
* ``analysis.shardcheck`` — abstract layout evaluation: runs a step
  function under ``jax.eval_shape`` with sharding-annotated inputs (no
  devices needed) and reports divisibility violations, implicit-reshard
  hotspots, and a per-op layout report diffed against a baseline.
* ``analysis.concurcheck`` — concurrency-registry coherence: proves the
  lock-order/lifecycle registries the CCY rules parse are internally
  coherent and byte-identical to what the runtime ordered-lock twin
  (``serving.locking``, armed via ``PADDLE_LOCKCHECK``) enforces.
* ``analysis.wirecheck`` — wire-registry coherence: proves the
  ``serving.wire.WIRE_SCHEMAS`` record registry the WIR rules parse is
  internally coherent (version pins, key-hash pins) and byte-identical
  to what the runtime sealing twin (``serving.wire.seal``, armed via
  ``PADDLE_WIRECHECK``) enforces at the producing/consuming seams.

Rule families (every id is greppable from this one table):

======== ====================================================================
family   meaning
======== ====================================================================
TPU000   meta: syntax error / unknown rule id in a suppression comment
TPU1xx   version-shim invariants: raw shard_map / axis_size / Pallas
         CompilerParams spelled outside ``utils/jax_compat.py``
TPU2xx   determinism: wall-clock or unseeded random in chaos-probed or
         jit-traced regions; probe sites absent from ``chaos.SITES``
TPU3xx   observability: metric names absent from ``instrument.CATALOG``
TPU4xx   exception hygiene: bare except; broad except swallowing
         ``CheckpointCorruptionError`` around checkpoint loads
TPU5xx   construction hygiene: mutable constructor defaults
TRC1xx   trace sanitizer: recompile hazards (scalar closures, python
         branches on tracers, retrace probe), host syncs, dead donations
TRC2xx   cross-rank collective schedules: order divergence, count mismatch
SHD1xx   static sharding/layout: unknown or duplicated mesh axes,
         collectives outside their region, in_specs arity, hard-coded
         mesh facts, donation/sharding mismatches
SHD2xx   abstract layout evaluation: sharded-dim divisibility, implicit
         reshard traffic over threshold, layout-report baseline drift
CCY1xx   serving concurrency: lock-order violations and foreign-lock
         grabs against serving/locking.py LOCK_ORDER, unguarded
         lock-protected writes, blocking calls under a lock,
         raise-into-driver telemetry paths, unguarded plane seams
CCY2xx   request lifecycle: state assignments outside
         scheduler.REQUEST_TRANSITIONS, terminal resolutions without
         exactly one terminal trace event
CCY5xx   concurrency-registry coherence: incoherent lock/lifecycle
         registries, static/runtime ordered-lock drift
WIR1xx   wire contracts: impure values in cross-process records,
         undeclared key writes/reads against WIRE_SCHEMAS, masked
         required reads, unversioned records, floats in
         prefix-key/crc positions, nondeterministic serialization
WIR5xx   wire-registry coherence: incoherent schema registry, schema
         edits without a version/key-hash bump, static/runtime drift
======== ====================================================================

The linter half (TPU/SHD1xx) is stdlib-only; the trace half (TRC) needs
JAX and loads lazily; the layout half (SHD2xx) imports JAX only inside
its functions — so ``import paddle_tpu.analysis`` stays cheap for
editors and CI.
"""
from __future__ import annotations

from . import concurcheck  # noqa: F401  (stdlib-only)
from . import schedule  # noqa: F401  (stdlib-only)
from . import wirecheck  # noqa: F401  (stdlib-only)
from . import shardcheck  # noqa: F401  (stdlib-only at import time)
from .astlint import (iter_python_files, lint_file, lint_paths,  # noqa: F401
                      lint_source)
from .concur_rules import (load_lock_order,  # noqa: F401
                           load_request_transitions)
from .concurcheck import CONCUR_RULES, concur_check  # noqa: F401
from .rules import (RULES, Finding, get_rule,  # noqa: F401
                    load_chaos_sites, load_flag_registry,
                    load_metric_catalog, rule_table)
from .shard_rules import load_known_axes  # noqa: F401
from .shardcheck import (SHARD_RULES, layout_check,  # noqa: F401
                         layout_report)
from .wire_rules import load_wire_schemas  # noqa: F401
from .wirecheck import WIRE_RULES, wire_check  # noqa: F401

__all__ = [
    "Finding", "RULES", "get_rule", "rule_table",
    "lint_source", "lint_file", "lint_paths", "iter_python_files",
    "load_chaos_sites", "load_flag_registry", "load_metric_catalog",
    "load_known_axes", "load_lock_order", "load_request_transitions",
    "SHARD_RULES", "layout_check", "layout_report", "shardcheck",
    "CONCUR_RULES", "concur_check", "concurcheck",
    "WIRE_RULES", "wire_check", "wirecheck", "load_wire_schemas",
    "schedule", "trace_check", "check_collective_schedules", "TRACE_RULES",
]

_LAZY = {"trace_check", "check_collective_schedules", "TRACE_RULES"}


def __getattr__(name):  # tracecheck imports jax; defer until first use
    if name in _LAZY or name == "tracecheck":
        # importlib, NOT `from . import ...`: the latter re-enters this
        # __getattr__ through _handle_fromlist and recurses
        import importlib
        mod = importlib.import_module(".tracecheck", __name__)
        return mod if name == "tracecheck" else getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
