"""WIR1xx — static wire-contract rules (the wirecheck fifth pass).

Every record that crosses (or will cross) a process/host boundary is
declared in ``serving/wire.py``'s ``WIRE_SCHEMAS`` — one entry per
family (kv_export_record, drain_manifest, fleet_signals,
autoscale_event, flight_dump, checkpoint_meta, telemetry_line) with its
version, required/optional keys, per-key JSON-pure type specs and the
functions that build/read/write it. These rules parse that registry
statically (``ast.literal_eval`` — no jax, no imports at lint time, the
same contract as every other ground-truth reader) and police the code
the registry names, so cross-process compatibility stops depending on
reviewer memory before ROADMAP 2's multi-host rungs put the records on
an actual wire.

Rules (all framework-only; suppress a line with
``# tpu-lint: disable=WIR101``):

  WIR101  non-wire-pure-value — a set/bytes/datetime/numpy-scalar/
          device-array expression flowing into a declared record key
          (device-typed keys, the KV payload plane, are exempt).
  WIR102  undeclared-key-write — a builder writes a key the family's
          schema does not declare: drift caught at the write site, not
          when a peer chokes on the file.
  WIR103  masked-required-read — a consumer reads an undeclared key, or
          ``.get()``s a key the schema marks REQUIRED (masking its
          absence with a default instead of failing at the seam). The
          version key is exempt: reading it via ``.get`` IS the
          version gate.
  WIR104  unversioned-record — a builder returns a record literal
          without the family's version key, or pins a version constant
          that contradicts the registry. (The registry-side half —
          a schema edited without a version bump — is WIR511 in
          ``analysis/wirecheck.py``.)
  WIR105  float-in-key-position — a float/str/object expression flowing
          into a ``prefix_keys``/``crc`` position: hash-chain prefix
          keys and routing keys must stay ints/tuples by construction
          or affinity breaks across hosts.
  WIR106  nondeterministic-serialization — iterating a set (or
          ``list(set(...))``) while building wire-tier content, or
          ``json.dump`` without ``sort_keys=True`` in a sink of a
          byte-stable family: byte-stability pins (tokens-crc, telemetry
          diffing) need deterministic order.

Registered into ``rules.RULES`` on import (rules.py imports this module
at the bottom of its own body, after concur_rules).
"""
from __future__ import annotations

import ast
import functools
import os
from typing import Dict, Iterable, List, Optional, Tuple

from .rules import (FileContext, _finding, _literal_from_source,
                    _own_body_walk, _PKG_ROOT, _register)

__all__ = ["load_wire_schemas", "load_non_wire_sinks", "wire_tail"]

_WIRE_PATH = os.path.join(_PKG_ROOT, "serving", "wire.py")


# -- static ground-truth readers ----------------------------------------------
@functools.lru_cache(maxsize=1)
def _wire_registry():
    return (_literal_from_source(_WIRE_PATH, "WIRE_SCHEMAS"),
            tuple(_literal_from_source(_WIRE_PATH, "NON_WIRE_SINKS")))


def load_wire_schemas() -> Dict[str, dict]:
    """{family: schema entry}, read statically from serving/wire.py's
    WIRE_SCHEMAS registry (the runtime twin loads the same literal —
    WIR520 pins the two views identical)."""
    return dict(_wire_registry()[0])


def load_non_wire_sinks() -> Tuple[str, ...]:
    """Serving-tier JSON writers declared render-only (chrome traces):
    exempt from the registry-drift gate."""
    return _wire_registry()[1]


def wire_tail(path: str) -> str:
    """The registry's file spelling: last two path components."""
    return "/".join(path.replace(os.sep, "/").split("/")[-2:])


# -- per-file binding ---------------------------------------------------------
class _WireInfo:
    __slots__ = ("builders", "consumers", "item_consumers", "sinks",
                 "wire_file")

    def __init__(self):
        # function name -> [family, ...] / [(family, var), ...]
        self.builders: Dict[str, List[str]] = {}
        self.consumers: Dict[str, List[Tuple[str, str]]] = {}
        self.item_consumers: Dict[str, List[Tuple[str, str]]] = {}
        self.sinks: Dict[str, List[str]] = {}
        self.wire_file = False      # any binding at all (WIR106 scope)


def _wire_info(ctx: FileContext) -> _WireInfo:
    cached = getattr(ctx, "_wir_info", None)
    if cached is not None:
        return cached
    info = _WireInfo()
    tail = wire_tail(ctx.path)
    for fam, spec in load_wire_schemas().items():
        for spelling in spec["builders"]:
            fspec, _, fname = spelling.partition("::")
            if fspec == tail:
                info.builders.setdefault(fname, []).append(fam)
        for spelling, var in spec["consumers"]:
            fspec, _, fname = spelling.partition("::")
            if fspec == tail:
                info.consumers.setdefault(fname, []).append((fam, var))
        for spelling, var in spec["item_consumers"]:
            fspec, _, fname = spelling.partition("::")
            if fspec == tail:
                info.item_consumers.setdefault(fname, []).append(
                    (fam, var))
        for spelling in spec["sinks"]:
            fspec, _, fname = spelling.partition("::")
            if fspec == tail:
                info.sinks.setdefault(fname, []).append(fam)
    info.wire_file = bool(info.builders or info.consumers or info.sinks)
    ctx._wir_info = info
    return info


def _module_const(ctx: FileContext, name: str):
    """Module-level ``NAME = <constant>`` value, or None."""
    for node in ctx.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == name \
                and isinstance(node.value, ast.Constant):
            return node.value.value
    return None


def _record_roots(fn, vkey: str) -> List[ast.Dict]:
    """Dict literals in ``fn`` whose top-level keys include the
    family's version key — the record-construction sites."""
    roots = []
    for n in _own_body_walk(fn):
        if isinstance(n, ast.Dict):
            for k in n.keys:
                if isinstance(k, ast.Constant) and k.value == vkey:
                    roots.append(n)
                    break
    return roots


def _record_vars(fn, fam: str, schemas: Dict[str, dict]) -> set:
    """Names in ``fn`` bound to a record of ``fam``: assigned from a
    record-root dict literal, or from a call to another declared
    builder of the SAME family (``record = pool.export_pages(...)``)."""
    vkey = schemas[fam]["version_key"]
    bare = {s.partition("::")[2] for s in schemas[fam]["builders"]}
    out = set()
    for n in _own_body_walk(fn):
        if not isinstance(n, ast.Assign) or len(n.targets) != 1 \
                or not isinstance(n.targets[0], ast.Name):
            continue
        v = n.value
        if isinstance(v, ast.Dict) and any(
                isinstance(k, ast.Constant) and k.value == vkey
                for k in v.keys):
            out.add(n.targets[0].id)
        elif isinstance(v, ast.Call):
            callee = v.func.attr if isinstance(v.func, ast.Attribute) \
                else getattr(v.func, "id", None)
            if callee in bare:
                out.add(n.targets[0].id)
    return out


def _writes_in(fn, fam: str, schemas: Dict[str, dict]
               ) -> Iterable[Tuple[str, ast.AST, ast.AST]]:
    """(key, value expr, report node) for every statically visible
    write into a record of ``fam`` inside ``fn``: record-root literal
    entries, item-row literal entries, and subscript stores on tracked
    record variables."""
    spec = schemas[fam]
    vkey = spec["version_key"]
    item_req = spec["item_required"]
    rvars = _record_vars(fn, fam, schemas)
    roots = []
    for n in _own_body_walk(fn):
        if isinstance(n, ast.Dict):
            keys = [k.value for k in n.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)]
            if vkey in keys:
                roots.append(n)
            elif item_req and len(set(keys) & set(item_req)) >= 2:
                # an item-row literal (shares >= 2 required row keys)
                for k, v in zip(n.keys, n.values):
                    if isinstance(k, ast.Constant) \
                            and isinstance(k.value, str):
                        yield ("\0item\0" + k.value, v, k)
        elif isinstance(n, ast.Assign) and len(n.targets) == 1 \
                and isinstance(n.targets[0], ast.Subscript) \
                and isinstance(n.targets[0].value, ast.Name) \
                and n.targets[0].value.id in rvars \
                and isinstance(n.targets[0].slice, ast.Constant) \
                and isinstance(n.targets[0].slice.value, str):
            yield (n.targets[0].slice.value, n.value, n.targets[0])
    for root in roots:
        for k, v in zip(root.keys, root.values):
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                yield (k.value, v, k)


# -- impurity classifiers -----------------------------------------------------
_IMPURE_CTORS = {"set", "frozenset", "bytes", "bytearray"}
_IMPURE_DOTTED_PREFIXES = ("numpy.", "jax.numpy.", "jnp.")
_IMPURE_DOTTED = {"datetime.datetime.now", "datetime.datetime.utcnow",
                  "datetime.date.today", "datetime.datetime.today",
                  "jax.device_put", "jax.numpy.asarray"}
_IMPURE_METHODS = {"tobytes", "numpy"}


def _impure_reason(ctx: FileContext, node) -> Optional[str]:
    """Why ``node`` can never be a wire-pure value, or None."""
    if isinstance(node, (ast.Set, ast.SetComp)):
        return "a set (unordered, not JSON)"
    if isinstance(node, ast.Constant) and isinstance(node.value, bytes):
        return "bytes (not JSON)"
    if isinstance(node, ast.Call):
        callee = getattr(node.func, "id", None)
        if callee in _IMPURE_CTORS:
            return f"{callee}() (not JSON)"
        if isinstance(node.func, ast.Attribute):
            if node.func.attr in _IMPURE_METHODS:
                return f".{node.func.attr}() (raw buffer/array)"
            dotted = ctx.dotted(node.func) or ""
            if dotted in _IMPURE_DOTTED:
                return f"{dotted}() (not JSON-stable)"
            if dotted.startswith(_IMPURE_DOTTED_PREFIXES):
                return (f"{dotted}() (numpy/device scalar — wrap in "
                        f"int()/float()/.tolist())")
    return None


def _nonint_reason(node) -> Optional[str]:
    """Why ``node`` can never be an int/int-tuple key, or None."""
    if isinstance(node, ast.Constant):
        if isinstance(node.value, float):
            return f"the float literal {node.value!r}"
        if isinstance(node.value, str):
            return f"the str literal {node.value!r}"
    if isinstance(node, (ast.Dict, ast.Set, ast.SetComp)):
        return "a dict/set"
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Div):
        return "a true division (float)"
    if isinstance(node, ast.Call):
        callee = getattr(node.func, "id", None)
        if callee == "float":
            return "float()"
        if callee == "round" and len(node.args) == 2:
            return "round(x, n) (float)"
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "time":
            return "a wall-clock float"
    return None


# =============================================================================
# WIR101/102/104/105 — producer-side rules
# =============================================================================
@_register(
    "WIR101", "non-wire-pure-value",
    "a set/bytes/datetime/numpy-scalar/device expression flows into a "
    "declared wire record: it will not survive a JSON hop between "
    "hosts (device-typed keys are the exempt payload plane)",
    "convert at the write site: sorted(...) for sets, int()/float()/"
    ".tolist() for numpy, an epoch float for datetimes",
    framework_only=True)
def _rule_wir101(ctx: FileContext):
    info = _wire_info(ctx)
    if not info.builders:
        return
    schemas = load_wire_schemas()
    for fn in ctx.functions():
        for fam in info.builders.get(fn.name, ()):
            spec = schemas[fam]
            for key, value, rep in _writes_in(fn, fam, schemas):
                if key.startswith("\0item\0"):
                    key = key[6:]
                    tspec = spec["item_required"].get(key) \
                        or spec["item_optional"].get(key, "")
                else:
                    tspec = spec["required"].get(key) \
                        or spec["optional"].get(key, "")
                if tspec == "device":
                    continue
                reason = _impure_reason(ctx, value)
                if reason:
                    yield _finding(
                        ctx_rule("WIR101"), ctx, rep,
                        f"{fam} record key '{key}' is assigned "
                        f"{reason} in {fn.name}()")


@_register(
    "WIR102", "undeclared-key-write",
    "a builder writes a key absent from the family's WIRE_SCHEMAS "
    "entry — schema drift at the write site, invisible until a peer "
    "process chokes on the record",
    "declare the key (with a type spec) in serving/wire.py and bump "
    "the family version, or drop the write",
    framework_only=True)
def _rule_wir102(ctx: FileContext):
    info = _wire_info(ctx)
    if not info.builders:
        return
    schemas = load_wire_schemas()
    for fn in ctx.functions():
        for fam in info.builders.get(fn.name, ()):
            spec = schemas[fam]
            declared = set(spec["required"]) | set(spec["optional"])
            item_declared = set(spec["item_required"]) \
                | set(spec["item_optional"])
            for key, _value, rep in _writes_in(fn, fam, schemas):
                if key.startswith("\0item\0"):
                    key = key[6:]
                    if key not in item_declared:
                        yield _finding(
                            ctx_rule("WIR102"), ctx, rep,
                            f"{fam} row key '{key}' written in "
                            f"{fn.name}() is not declared in the "
                            f"item schema")
                elif key not in declared:
                    yield _finding(
                        ctx_rule("WIR102"), ctx, rep,
                        f"{fam} key '{key}' written in {fn.name}() "
                        f"is not declared in WIRE_SCHEMAS")


@_register(
    "WIR103", "masked-required-read",
    "a consumer reads an undeclared key, or .get()s a key the schema "
    "marks REQUIRED — the default masks a torn/drifted record instead "
    "of failing at the seam (the version key is exempt: reading it "
    "via .get IS the version gate)",
    "index required keys directly (record['key']); declare new keys "
    "in serving/wire.py before reading them",
    framework_only=True)
def _rule_wir103(ctx: FileContext):
    info = _wire_info(ctx)
    if not (info.consumers or info.item_consumers):
        return
    schemas = load_wire_schemas()
    for fn in ctx.functions():
        bindings = []
        for fam, var in info.consumers.get(fn.name, ()):
            spec = schemas[fam]
            bindings.append((fam, var, spec["version_key"],
                             spec["required"], spec["optional"]))
        for fam, var in info.item_consumers.get(fn.name, ()):
            spec = schemas[fam]
            bindings.append((fam, var, None, spec["item_required"],
                             spec["item_optional"]))
        for fam, var, vkey, required, optional in bindings:
            for n in _own_body_walk(fn):
                key = None
                masked = False
                if isinstance(n, ast.Subscript) \
                        and isinstance(n.value, ast.Name) \
                        and n.value.id == var \
                        and isinstance(n.ctx, ast.Load) \
                        and isinstance(n.slice, ast.Constant) \
                        and isinstance(n.slice.value, str):
                    key = n.slice.value
                elif isinstance(n, ast.Call) \
                        and isinstance(n.func, ast.Attribute) \
                        and n.func.attr == "get" \
                        and isinstance(n.func.value, ast.Name) \
                        and n.func.value.id == var \
                        and n.args \
                        and isinstance(n.args[0], ast.Constant) \
                        and isinstance(n.args[0].value, str):
                    key = n.args[0].value
                    masked = True
                if key is None or key == vkey:
                    continue
                if key not in required and key not in optional:
                    yield _finding(
                        ctx_rule("WIR103"), ctx, n,
                        f"{fn.name}() reads undeclared {fam} key "
                        f"'{key}' from '{var}'")
                elif masked and key in required:
                    yield _finding(
                        ctx_rule("WIR103"), ctx, n,
                        f"{fn.name}() .get()s required {fam} key "
                        f"'{key}' — a missing key must fail at the "
                        f"seam, not default through")


@_register(
    "WIR104", "unversioned-record",
    "a builder returns a record without the family's version key, or "
    "pins a version constant that contradicts WIRE_SCHEMAS — the "
    "consumer generation gate cannot work on unversioned records",
    "write the version key first ('version': N matching the registry); "
    "schema edits bump the version AND append a key_hashes pin",
    framework_only=True)
def _rule_wir104(ctx: FileContext):
    info = _wire_info(ctx)
    if not info.builders:
        return
    schemas = load_wire_schemas()
    for fn in ctx.functions():
        for fam in info.builders.get(fn.name, ()):
            spec = schemas[fam]
            vkey = spec["version_key"]
            for n in _own_body_walk(fn):
                if not isinstance(n, ast.Return):
                    continue
                ret = n.value
                if isinstance(ret, ast.Call):
                    # look through `return seal({...}, fam)` wrappers
                    dicts = [a for a in ret.args
                             if isinstance(a, ast.Dict)]
                    ret = dicts[0] if dicts else None
                if not isinstance(ret, ast.Dict):
                    continue
                keys = [k.value for k in ret.keys
                        if isinstance(k, ast.Constant)]
                if vkey not in keys:
                    yield _finding(
                        ctx_rule("WIR104"), ctx, n,
                        f"{fn.name}() returns a {fam} record without "
                        f"its version key '{vkey}'")
            for root in _record_roots(fn, vkey):
                for k, v in zip(root.keys, root.values):
                    if not (isinstance(k, ast.Constant)
                            and k.value == vkey):
                        continue
                    got = None
                    if isinstance(v, ast.Constant):
                        got = v.value
                    elif isinstance(v, ast.Name):
                        got = _module_const(ctx, v.id)
                    if got is not None and got != spec["version"]:
                        yield _finding(
                            ctx_rule("WIR104"), ctx, k,
                            f"{fn.name}() pins {fam} {vkey}={got!r} "
                            f"but WIRE_SCHEMAS declares "
                            f"{spec['version']}")


@_register(
    "WIR105", "float-in-key-position",
    "a float/str/object expression flows into a prefix_keys/crc "
    "position: hash-chain prefix keys and routing keys must stay "
    "ints/tuples by construction (PYTHONHASHSEED-stable, "
    "JSON-roundtrip-exact) or cross-host affinity silently breaks",
    "keep key material integral: hash(tuple), int(), zlib.crc32 — "
    "never wall-clock floats, division results or str()",
    framework_only=True)
def _rule_wir105(ctx: FileContext):
    info = _wire_info(ctx)
    if not info.builders:
        return
    schemas = load_wire_schemas()
    for fn in ctx.functions():
        for fam in info.builders.get(fn.name, ()):
            spec = schemas[fam]
            for key, value, rep in _writes_in(fn, fam, schemas):
                plain = key[6:] if key.startswith("\0item\0") else key
                tspec = spec["required"].get(plain) \
                    or spec["optional"].get(plain) \
                    or spec["item_required"].get(plain) \
                    or spec["item_optional"].get(plain, "")
                if tspec not in ("prefix_keys", "crc"):
                    continue
                reason = _nonint_reason(value)
                if reason:
                    yield _finding(
                        ctx_rule("WIR105"), ctx, rep,
                        f"{fam} key position '{plain}' in {fn.name}() "
                        f"is assigned {reason}")


# =============================================================================
# WIR106 — deterministic serialization order
# =============================================================================
def _set_expr(node) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    return isinstance(node, ast.Call) \
        and getattr(node.func, "id", None) in ("set", "frozenset")


@_register(
    "WIR106", "nondeterministic-serialization",
    "set iteration (or list(set(...))) while building wire-tier "
    "content, or json.dump without sort_keys=True in a byte-stable "
    "family's sink — serialization order must be deterministic where "
    "a byte-stability pin (tokens-crc, telemetry diffing) exists",
    "iterate sorted(the_set) (key=str for mixed None/str), and pass "
    "sort_keys=True at byte-stable json.dump sites",
    framework_only=True)
def _rule_wir106(ctx: FileContext):
    info = _wire_info(ctx)
    if not info.wire_file:
        return
    schemas = load_wire_schemas()
    for fn in ctx.functions():
        # names bound to set expressions inside this function
        set_vars = {n.targets[0].id for n in _own_body_walk(fn)
                    if isinstance(n, ast.Assign)
                    and len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Name)
                    and _set_expr(n.value)}
        for n in _own_body_walk(fn):
            if isinstance(n, ast.For):
                it = n.iter
                if _set_expr(it) or (isinstance(it, ast.Name)
                                     and it.id in set_vars):
                    yield _finding(
                        ctx_rule("WIR106"), ctx, n,
                        f"{fn.name}() iterates a set — element order "
                        f"is arbitrary, so the built record is not "
                        f"byte-stable")
            elif isinstance(n, ast.Call) \
                    and getattr(n.func, "id", None) in ("list",
                                                        "tuple") \
                    and n.args and _set_expr(n.args[0]):
                yield _finding(
                    ctx_rule("WIR106"), ctx, n,
                    f"{fn.name}() materializes a set in arbitrary "
                    f"order — wrap it in sorted(...)")
        # json.dump without sort_keys in a byte-stable family's sink
        byte_stable = any(schemas[fam]["byte_stable"]
                          for fam in info.sinks.get(fn.name, ()))
        if not byte_stable:
            continue
        for n in _own_body_walk(fn):
            if isinstance(n, ast.Call) \
                    and (ctx.dotted(n.func) or "") in ("json.dump",
                                                       "json.dumps"):
                sorts = any(kw.arg == "sort_keys"
                            and isinstance(kw.value, ast.Constant)
                            and kw.value.value is True
                            for kw in n.keywords)
                if not sorts:
                    yield _finding(
                        ctx_rule("WIR106"), ctx, n,
                        f"{fn.name}() json.dumps a byte-stable family "
                        f"without sort_keys=True")


# _finding takes a Rule; resolve lazily so decorator order cannot bite
def ctx_rule(rule_id: str):
    from .rules import RULES
    return RULES[rule_id]
