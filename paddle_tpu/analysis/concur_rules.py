"""CCY1xx/2xx — static concurrency + lifecycle rules (concurcheck).

The serving tier coordinates four RLocks with a declared partial order,
a never-raise-into-``step_all`` dump discipline, a one-``is None``-check
disarm convention, and a WAITING/RUNNING/HANDOFF/FINISHED request state
machine. Every one of those invariants used to be enforced only by
tests and reviewer memory — PR 17's autoscaler reaching straight into
``router._lock`` is exactly the drift that accumulates. These rules
make the machine-checkable subset a lint gate.

Ground truth is read statically (``ast.literal_eval`` — no jax, no
imports at lint time, the same contract as the chaos-site/metric/axis
rules):

  * ``serving/locking.py`` — ``LOCK_ORDER`` (the declared partial
    order, outermost first), ``LOCK_OWNERS`` (class -> lock name, how
    ``with self._lock`` resolves), ``LOCK_BEARERS`` (variable/attribute
    spellings -> lock name, how ``with eng._lock`` resolves) and
    ``LOCK_CORE_MODULES`` (the serving files blessed to take another
    component's private lock directly). The runtime twin
    (``locking.OrderedLock``, armed via ``PADDLE_LOCKCHECK``) reads the
    SAME registry, so the static and dynamic halves cannot drift
    (test-pinned).
  * ``serving/scheduler.py`` — ``REQUEST_TRANSITIONS``, the canonical
    request-lifecycle table ("new" is the pre-lifecycle pseudo-state a
    fresh Request is born from).

Rules (all framework-only; suppress a line with
``# tpu-lint: disable=CCY101``):

  CCY101  lock-order-violation / foreign-lock-grab — a nested
          ``with X._lock`` under ``with Y._lock`` (including one level
          of same-file call-graph resolution) whose edge contradicts
          LOCK_ORDER; or a serving module outside LOCK_CORE_MODULES
          grabbing another component's private ``_lock`` directly.
  CCY102  unguarded-attr-write — an attribute a lock-owning class
          assigns under ``with self._lock`` written from a public
          method outside the lock.
  CCY103  blocking-call-under-lock — ``time.sleep``, argless
          ``.join()``, store ops, ``block_until_ready``, ``.item()``
          while holding a lock.
  CCY104  raise-into-driver — a dump/telemetry/record path reachable
          from ``step()``/``step_all()`` (or bearing a canonical
          never-raise seam name) whose body is not exception-contained.
  CCY105  unguarded-plane-seam — an observer/memwatch/fleet-obs seam
          calling an ``on_*``/``record_*``/``note_*``/``write_*``
          method without the single ``is None`` disarm guard.
  CCY201  illegal-state-transition — a ``req.state = ...`` assignment
          outside REQUEST_TRANSITIONS, or a terminal finish/fail path
          with zero or two terminal trace events (the exactly-one
          terminal-event contract).

Registered into ``rules.RULES`` on import (rules.py imports this module
at the bottom of its own body, after shard_rules).
"""
from __future__ import annotations

import ast
import functools
import os
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from .rules import (RULES, FileContext, _finding, _literal_from_source,
                    _own_body_walk, _PKG_ROOT, _register)

__all__ = ["load_lock_order", "load_lock_owners", "load_lock_bearers",
           "load_lock_core_modules", "load_request_transitions"]


# -- static ground-truth readers ----------------------------------------------
@functools.lru_cache(maxsize=1)
def _lock_registry():
    path = os.path.join(_PKG_ROOT, "serving", "locking.py")
    return (tuple(_literal_from_source(path, "LOCK_ORDER")),
            dict(_literal_from_source(path, "LOCK_OWNERS")),
            dict(_literal_from_source(path, "LOCK_BEARERS")),
            tuple(_literal_from_source(path, "LOCK_CORE_MODULES")))


def load_lock_order() -> Tuple[str, ...]:
    """The declared lock partial order (outermost first), read
    statically from serving/locking.py's LOCK_ORDER registry."""
    return _lock_registry()[0]


def load_lock_owners() -> Dict[str, str]:
    """class name -> lock name (how ``with self._lock`` resolves)."""
    return dict(_lock_registry()[1])


def load_lock_bearers() -> Dict[str, str]:
    """variable/attribute spelling -> lock name (how ``with
    eng._lock`` / ``with self.router._lock`` resolve)."""
    return dict(_lock_registry()[2])


def load_lock_core_modules() -> Tuple[str, ...]:
    """Serving modules blessed to take another component's private
    lock directly."""
    return _lock_registry()[3]


@functools.lru_cache(maxsize=1)
def load_request_transitions() -> Dict[str, Tuple[str, ...]]:
    """The canonical request-lifecycle table, read statically from
    serving/scheduler.py's REQUEST_TRANSITIONS."""
    path = os.path.join(_PKG_ROOT, "serving", "scheduler.py")
    table = _literal_from_source(path, "REQUEST_TRANSITIONS")
    return {k: tuple(v) for k, v in table.items()}


def _rank() -> Dict[str, int]:
    order = load_lock_order()
    return {name: i for i, name in enumerate(order)}


def _is_serving_path(path: str) -> bool:
    return "/serving/" in os.path.abspath(path).replace(os.sep, "/")


# -- lock-expression resolution -----------------------------------------------
def _bearer_tail(node) -> Optional[str]:
    """The name a lock-holding expression is spelled through:
    ``eng`` -> "eng", ``self.router`` -> "router",
    ``self.replicas[i]`` -> "replicas"."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _lock_base(expr) -> Optional[ast.AST]:
    """The holder expression of a ``<holder>._lock`` spelling (the
    with-item form every serving lock acquisition uses)."""
    if isinstance(expr, ast.Attribute) and expr.attr == "_lock":
        return expr.value
    return None


class _FileInfo:
    """Per-file concurrency facts shared by the CCY checkers (computed
    once per FileContext, cached on the ctx object)."""

    def __init__(self, ctx: FileContext):
        self.owners = load_lock_owners()
        self.bearers = load_lock_bearers()
        # enclosing class for every function defined directly in a
        # class body (methods), by node identity
        self.class_of: Dict[int, str] = {}
        self.classes: List[ast.ClassDef] = []
        for node in ctx.nodes():
            if isinstance(node, ast.ClassDef):
                self.classes.append(node)
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self.class_of[id(item)] = node.name
        self.functions = ctx.functions()
        # function name -> lock names its own body acquires (for the
        # one-level call-graph resolution in CCY101)
        self.acquired_by_name: Dict[str, Set[str]] = {}
        for fn in self.functions:
            acq = set()
            for n in _own_body_walk(fn):
                if isinstance(n, ast.With):
                    for item in n.items:
                        name = self.resolve_lock(item.context_expr, fn)
                        if name is not None:
                            acq.add(name)
            if acq:
                self.acquired_by_name.setdefault(fn.name, set()).update(acq)

    def resolve_lock(self, expr, fn) -> Optional[str]:
        """LOCK_ORDER name for a with-item context expression, or None
        when it is not a recognizable ordered-lock acquisition."""
        base = _lock_base(expr)
        if base is None:
            return None
        if isinstance(base, ast.Name):
            if base.id == "self":
                cls = self.class_of.get(id(fn))
                return self.owners.get(cls) if cls else None
            # one level of local-binding resolution: eng = self.replicas[i]
            for n in _own_body_walk(fn):
                if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                        isinstance(n.targets[0], ast.Name) and \
                        n.targets[0].id == base.id:
                    tail = _bearer_tail(n.value)
                    if tail is not None and tail in self.bearers:
                        return self.bearers[tail]
            return self.bearers.get(base.id)
        tail = _bearer_tail(base)
        return self.bearers.get(tail) if tail is not None else None


def _info(ctx: FileContext) -> _FileInfo:
    cached = getattr(ctx, "_ccy_info", None)
    if cached is None:
        cached = _FileInfo(ctx)
        ctx._ccy_info = cached
    return cached


# =============================================================================
# CCY101 — lock order / lock encapsulation
# =============================================================================
@_register(
    "CCY101", "lock-order-violation",
    "nested lock acquisition contradicting serving/locking.py "
    "LOCK_ORDER, or a private component lock grabbed outside the "
    "serving lock core",
    "the declared order (outermost first) is serving.locking.LOCK_ORDER "
    "(fleet_obs -> router -> engine -> observer): acquire strictly "
    "inner locks only, or release before re-entering an outer one. "
    "Outside the core modules (engine/router/obs/fleet_obs), never take "
    "another component's ._lock directly — call a public seam on the "
    "owner (e.g. router.live_by_role()) so the owner keeps its own "
    "critical sections. PADDLE_LOCKCHECK=1 arms the runtime twin "
    "(locking.OrderedLock) that catches the same inversions live.",
    framework_only=True)
def _check_lock_order(ctx: FileContext):
    rule = RULES["CCY101"]
    info = _info(ctx)
    rank = _rank()
    core = load_lock_core_modules()
    serving = _is_serving_path(ctx.path)
    base_name = os.path.basename(ctx.path)
    out: List = []

    def edge_findings(held: List[str], acq: str, node, via: str = ""):
        for h in held:
            if h != acq and rank[h] >= rank[acq]:
                suffix = f" (via call to {via}())" if via else ""
                out.append(_finding(
                    rule, ctx, node,
                    f"acquires lock '{acq}' while holding '{h}'"
                    f"{suffix}: contradicts LOCK_ORDER "
                    f"({' -> '.join(load_lock_order())})"))

    def check_calls(node, held: List[str]):
        # one level of same-file call-graph resolution while holding
        if not held or node is None:
            return
        for call in ast.walk(node):
            if not isinstance(call, ast.Call):
                continue
            f = call.func
            callee = None
            if isinstance(f, ast.Name):
                callee = f.id
            elif isinstance(f, ast.Attribute) and \
                    isinstance(f.value, ast.Name) and \
                    f.value.id in ("self", "cls"):
                callee = f.attr
            if callee is None:
                continue
            for acq in sorted(info.acquired_by_name.get(callee, ())):
                edge_findings(held, acq, call, via=callee)

    for fn in info.functions:
        def visit(stmts, held: List[str]):
            for st in stmts:
                if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                    continue
                if isinstance(st, ast.With):
                    inner = list(held)
                    for item in st.items:
                        name = info.resolve_lock(item.context_expr, fn)
                        if name is None:
                            continue
                        base = _lock_base(item.context_expr)
                        foreign = not (isinstance(base, ast.Name) and
                                       base.id == "self")
                        if foreign and serving and base_name not in core:
                            out.append(_finding(
                                rule, ctx, item.context_expr,
                                f"grabs component lock '{name}' directly "
                                f"from {base_name} (outside the serving "
                                f"lock core): use a public seam on the "
                                f"owning object"))
                        edge_findings(inner, name, item.context_expr)
                        if name not in inner:
                            inner.append(name)
                    visit(st.body, inner)
                elif isinstance(st, (ast.If, ast.While)):
                    check_calls(st.test, held)
                    visit(st.body, held)
                    visit(st.orelse, held)
                elif isinstance(st, ast.For):
                    check_calls(st.iter, held)
                    visit(st.body, held)
                    visit(st.orelse, held)
                elif isinstance(st, ast.Try):
                    visit(st.body, held)
                    for h in st.handlers:
                        visit(h.body, held)
                    visit(st.orelse, held)
                    visit(st.finalbody, held)
                else:
                    check_calls(st, held)

        visit(fn.body, [])
    return out


# =============================================================================
# CCY102 — guarded attributes leave the lock
# =============================================================================
def _self_attr_writes(stmt) -> Iterable[Tuple[ast.AST, str]]:
    """(node, attr) for every ``self.<attr>`` assignment target in one
    statement (plain, augmented, annotated, tuple-unpacked)."""
    targets: List[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    for t in targets:
        if isinstance(t, (ast.Tuple, ast.List)):
            stack = list(t.elts)
        else:
            stack = [t]
        for el in stack:
            if isinstance(el, ast.Attribute) and \
                    isinstance(el.value, ast.Name) and el.value.id == "self":
                yield el, el.attr


def _is_self_lock_item(expr) -> bool:
    base = _lock_base(expr)
    return isinstance(base, ast.Name) and base.id == "self"


@_register(
    "CCY102", "unguarded-attr-write",
    "attribute a lock-owning class assigns under `with self._lock` "
    "written from a public method outside the lock",
    "every attribute a class mutates under its own lock is part of that "
    "lock's protected state: public entry points must re-enter "
    "`with self._lock:` before writing it (private helpers are assumed "
    "to run under a caller's lock — the engine/scheduler convention).",
    framework_only=True)
def _check_guarded_attr_writes(ctx: FileContext):
    rule = RULES["CCY102"]
    info = _info(ctx)
    out: List = []
    for cls in info.classes:
        methods = [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        init = next((m for m in methods if m.name == "__init__"), None)
        if init is None:
            continue
        owns_lock = any(
            attr == "_lock"
            for st in _own_body_walk(init)
            for _, attr in _self_attr_writes(st))
        if not owns_lock:
            continue
        # the lock-protected attribute set: everything any method of
        # this class assigns under `with self._lock`
        guarded: Set[str] = set()
        for m in methods:
            for w in _own_body_walk(m):
                if not isinstance(w, ast.With) or \
                        not any(_is_self_lock_item(i.context_expr)
                                for i in w.items):
                    continue
                for st in w.body:
                    stack = [st]
                    while stack:
                        n = stack.pop()
                        if isinstance(n, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                            continue
                        for _, attr in _self_attr_writes(n):
                            guarded.add(attr)
                        stack.extend(ast.iter_child_nodes(n))
        guarded.discard("_lock")
        if not guarded:
            continue
        for m in methods:
            if m.name.startswith("_"):
                continue              # private: runs under a caller's lock

            def visit(stmts, locked: bool):
                for st in stmts:
                    if isinstance(st, (ast.FunctionDef,
                                       ast.AsyncFunctionDef, ast.ClassDef)):
                        continue
                    if isinstance(st, ast.With):
                        inner = locked or any(
                            _is_self_lock_item(i.context_expr)
                            for i in st.items)
                        visit(st.body, inner)
                        continue
                    if not locked:
                        for node, attr in _self_attr_writes(st):
                            if attr in guarded:
                                out.append(_finding(
                                    rule, ctx, node,
                                    f"public {cls.name}.{m.name}() writes "
                                    f"lock-guarded attribute "
                                    f"'self.{attr}' outside "
                                    f"`with self._lock`"))
                    for field in ("body", "orelse", "finalbody"):
                        sub = getattr(st, field, None)
                        if sub:
                            visit(sub, locked)
                    for h in getattr(st, "handlers", ()):
                        visit(h.body, locked)

            visit(m.body, False)
    return out


# =============================================================================
# CCY103 — blocking calls while holding a lock
# =============================================================================
_STORE_BLOCKING_ATTRS = ("get", "set", "add", "wait", "barrier", "check")


def _is_lockish_item(expr) -> bool:
    if _lock_base(expr) is not None:
        return True
    return isinstance(expr, ast.Name) and (expr.id == "lock" or
                                           expr.id.endswith("_lock"))


def _blocking_kind(ctx: FileContext, call: ast.Call) -> Optional[str]:
    d = ctx.dotted(call.func)
    if d and (d == "time.sleep" or d.endswith(".time.sleep")):
        return "time.sleep"
    if d and (d == "block_until_ready" or
              d.endswith(".block_until_ready")):
        return "block_until_ready()"
    if not isinstance(call.func, ast.Attribute):
        return None
    attr = call.func.attr
    if attr == "block_until_ready":
        return ".block_until_ready()"
    if attr == "item" and not call.args and not call.keywords:
        return ".item() host sync"
    if attr == "join" and not call.args and \
            all(k.arg == "timeout" for k in call.keywords):
        # argless (or timeout=) join is a thread join; str.join always
        # takes the iterable positionally
        return ".join() thread wait"
    if attr in _STORE_BLOCKING_ATTRS:
        recv = (ctx.dotted(call.func.value) or
                _bearer_tail(call.func.value) or "")
        if "store" in recv.lower():
            return f"store.{attr}() cross-host op"
    return None


@_register(
    "CCY103", "blocking-call-under-lock",
    "blocking call (time.sleep / thread .join() / store ops / "
    "block_until_ready / .item()) while holding a lock",
    "a blocking call inside a critical section serializes every thread "
    "behind the sleeper — and a cross-host store op or device sync can "
    "hold the lock for unbounded time (the classic serving stall). Move "
    "the wait outside the `with ... _lock:` block (the engine does its "
    "dispatch/telemetry AFTER releasing) or use a Condition with a "
    "timeout.",
    framework_only=True)
def _check_blocking_under_lock(ctx: FileContext):
    rule = RULES["CCY103"]
    info = _info(ctx)
    out: List = []
    for fn in info.functions:
        flagged: Set[int] = set()
        for w in _own_body_walk(fn):
            if not isinstance(w, ast.With) or \
                    not any(_is_lockish_item(i.context_expr)
                            for i in w.items):
                continue
            stack: List[ast.AST] = list(w.body)
            while stack:
                n = stack.pop()
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                    continue
                if isinstance(n, ast.Call) and id(n) not in flagged:
                    kind = _blocking_kind(ctx, n)
                    if kind is not None:
                        flagged.add(id(n))
                        out.append(_finding(
                            rule, ctx, n,
                            f"blocking {kind} while holding a lock"))
                stack.extend(ast.iter_child_nodes(n))
    return out


# =============================================================================
# CCY104 — the never-raise-into-the-driver discipline
# =============================================================================
#: canonical never-raise seam names: methods the step_all driver loop
#: (or the engine step) reaches on every pass — fleet sampling, the
#: autoscaler control tick, telemetry streaming, flight dumps. Their
#: whole body must be fenced (`try: ... except Exception: log`).
_NEVER_RAISE_NAMES = ("on_step_all", "on_autoscale_event",
                      "write_telemetry", "dump", "control")
#: name shapes that make a same-file callee of step()/step_all() part
#: of the dump/telemetry/record path
_TELEMETRYISH_PREFIXES = ("dump", "record_", "write_", "note_",
                          "on_step")
#: calls a never-raise prologue/epilogue may make outside the fence:
#: the instrumentation plane's bounded-metric recorders (no-raise by
#: construction) and logging
_BLESSED_CALL_HEADS = ("logger.", "logging.", "_instr.record_",
                       "instrument.record_")
_SAFE_CALLS = frozenset({
    "time.monotonic", "time.time", "len", "int", "float", "bool", "str",
    "list", "dict", "tuple", "set", "getattr", "min", "max", "sorted",
    "isinstance", "id", "repr", "format", "round"})


def _blessed_call(ctx: FileContext, call: ast.Call) -> bool:
    """A prologue/epilogue call a never-raise body may make outside the
    fence. Matched both on the resolved dotted path AND the raw
    spelling: ``ctx.dotted`` expands import aliases (``_instr.record_x``
    resolves to ``..profiler.instrument.record_x``), so the head check
    alone would miss the aliased spelling every serving module uses."""
    d = ctx.dotted(call.func) or ""
    if d.startswith(_BLESSED_CALL_HEADS):
        return True
    f = call.func
    if isinstance(f, ast.Attribute):
        tail = _bearer_tail(f.value)
        if f.attr.startswith("record_") and tail in ("_instr", "instrument"):
            return True
        if tail in ("logger", "logging", "log", "_log"):
            return True
    return False


def _safe_expr(ctx: FileContext, e) -> bool:
    """Conservatively raise-free prologue expression: names, attribute
    reads, constants, and arithmetic/boolean/conditional compositions
    of those (plus a tiny blessed-call set like time.monotonic)."""
    if e is None or isinstance(e, (ast.Constant, ast.Name)):
        return True
    if isinstance(e, ast.Attribute):
        return _safe_expr(ctx, e.value)
    if isinstance(e, ast.BoolOp):
        return all(_safe_expr(ctx, v) for v in e.values)
    if isinstance(e, (ast.UnaryOp,)):
        return _safe_expr(ctx, e.operand)
    if isinstance(e, ast.BinOp):
        return _safe_expr(ctx, e.left) and _safe_expr(ctx, e.right)
    if isinstance(e, ast.Compare):
        return _safe_expr(ctx, e.left) and \
            all(_safe_expr(ctx, c) for c in e.comparators)
    if isinstance(e, ast.IfExp):
        return all(_safe_expr(ctx, x) for x in (e.test, e.body, e.orelse))
    if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
        return all(_safe_expr(ctx, x) for x in e.elts)
    if isinstance(e, ast.Dict):
        return all(_safe_expr(ctx, x) for x in
                   list(e.keys) + list(e.values) if x is not None)
    if isinstance(e, ast.Call):
        d = ctx.dotted(e.func) or ""
        if d in _SAFE_CALLS or _blessed_call(ctx, e):
            return all(_safe_expr(ctx, a) for a in e.args) and \
                all(_safe_expr(ctx, k.value) for k in e.keywords)
        return False
    return False


def _broad_handler(handlers) -> bool:
    for h in handlers:
        if h.type is None:
            return True
        names = []
        t = h.type
        if isinstance(t, ast.Tuple):
            names = [getattr(x, "attr", getattr(x, "id", "")) for x in t.elts]
        else:
            names = [getattr(t, "attr", getattr(t, "id", ""))]
        if any(n in ("Exception", "BaseException") for n in names):
            return True
    return False


def _exception_contained(ctx: FileContext, fn) -> bool:
    """True when every statement of fn's body that can plausibly raise
    sits inside a try whose handlers catch (at least) Exception — the
    never-raise fence — allowing a raise-free prologue (docstring,
    simple bindings, early-return guards) and a blessed epilogue
    (logging / instrumentation counters / plain returns)."""
    fenced = False
    for st in fn.body:
        if isinstance(st, ast.Expr) and isinstance(st.value, ast.Constant):
            continue                                   # docstring
        if isinstance(st, (ast.Pass, ast.Global, ast.Nonlocal)):
            continue
        if isinstance(st, ast.Try):
            if not _broad_handler(st.handlers):
                return False
            fenced = True
            continue
        if isinstance(st, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            if _safe_expr(ctx, st.value):
                continue
            return False
        if isinstance(st, ast.If):
            if not _safe_expr(ctx, st.test):
                return False
            ok = all(isinstance(b, (ast.Return, ast.Pass, ast.Continue,
                                    ast.Break))
                     or (isinstance(b, (ast.Assign, ast.AnnAssign)) and
                         _safe_expr(ctx, b.value))
                     for b in st.body) and not st.orelse
            if ok and all(_safe_expr(ctx, getattr(b, "value", None))
                          for b in st.body if isinstance(b, ast.Return)):
                continue
            return False
        if isinstance(st, ast.Return):
            if _safe_expr(ctx, st.value):
                continue
            return False
        if isinstance(st, ast.Expr) and isinstance(st.value, ast.Call):
            if _blessed_call(ctx, st.value):
                continue
            return False
        return False
    return fenced


@_register(
    "CCY104", "raise-into-driver",
    "dump/telemetry/record path reachable from step()/step_all() whose "
    "body is not exception-contained",
    "observability must never wound: anything the driver loop reaches "
    "on its step path (flight dumps, telemetry writes, fleet sampling, "
    "the autoscaler control tick) wraps its whole body in `try: ... "
    "except Exception: logger.warning(...)` so a postmortem/telemetry "
    "bug cannot kill the serving loop it is observing.",
    framework_only=True)
def _check_never_raise(ctx: FileContext):
    rule = RULES["CCY104"]
    info = _info(ctx)
    out: List = []
    by_name: Dict[str, List] = {}
    for fn in info.functions:
        by_name.setdefault(fn.name, []).append(fn)

    def called_names(fn) -> Set[str]:
        names = set()
        for n in _own_body_walk(fn):
            if isinstance(n, ast.Call):
                f = n.func
                if isinstance(f, ast.Attribute):
                    names.add(f.attr)
                elif isinstance(f, ast.Name):
                    names.add(f.id)
        return names

    # same-file reachability from step()/step_all(), one call level deep
    reachable: Set[str] = set()
    for entry in info.functions:
        if entry.name not in ("step", "step_all"):
            continue
        direct = called_names(entry)
        reachable |= direct
        for callee in direct:
            for f in by_name.get(callee, ()):
                reachable |= called_names(f)
    candidates = {n for n in reachable
                  if n.startswith(_TELEMETRYISH_PREFIXES)}

    checked: Set[int] = set()
    for fn in info.functions:
        on_path = fn.name in candidates
        canonical = fn.name in _NEVER_RAISE_NAMES and \
            _is_serving_path(ctx.path)
        if not (on_path or canonical) or id(fn) in checked:
            continue
        checked.add(id(fn))
        if not _exception_contained(ctx, fn):
            where = "reachable from the step driver" if on_path else \
                "a canonical never-raise seam"
            out.append(_finding(
                rule, ctx, fn,
                f"'{fn.name}' is {where} but its body is not "
                f"exception-contained (no broad try/except fence)"))
    return out


# =============================================================================
# CCY105 — the one-`is None`-check disarm convention
# =============================================================================
_PLANES = ("obs", "fleet_obs", "memwatch", "watcher")
_SEAM_PREFIXES = ("on_", "record_", "note_", "write_")


def _dotted_text(node) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _plane_key(base) -> Optional[str]:
    tail = _bearer_tail(base)
    if tail is None or tail.lstrip("_") not in _PLANES:
        return None
    return _dotted_text(base)


@_register(
    "CCY105", "unguarded-plane-seam",
    "observability-plane seam call (on_*/record_*/note_*/write_*) "
    "without the single `is None` disarm guard",
    "disarmed planes are None by contract (obs/fleet_obs/memwatch): "
    "every seam costs exactly one guard — `if self.obs is not None: "
    "self.obs.on_x(...)` (or the bound-alias form `obs = self.obs; "
    "armed = obs is not None and obs.armed`). An unguarded call is an "
    "AttributeError on every disarmed run.",
    framework_only=True)
def _check_plane_guards(ctx: FileContext):
    rule = RULES["CCY105"]
    info = _info(ctx)
    out: List = []

    for fn in info.functions:
        env_alias: Dict[str, str] = {}
        env_flag: Dict[str, FrozenSet[str]] = {}
        # the armed-parameter convention: a caller computes
        # `armed = obs is not None and obs.armed` once and threads the
        # flag into its private helpers (`_run_plan(plan, armed=...)`)
        # — inside those helpers `if armed:` IS the disarm guard
        for a in (fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs):
            if a.arg == "armed" or a.arg.endswith("_armed"):
                env_flag[a.arg] = frozenset(
                    {"self.obs", "obs", "self.fleet_obs", "fleet_obs"})

        def expand(keys: Set[str]) -> Set[str]:
            full = set(keys)
            for k in keys:
                if k in env_alias:
                    full.add(env_alias[k])
            return full

        def guard_keys(test) -> Tuple[Set[str], Set[str]]:
            pos: Set[str] = set()
            neg: Set[str] = set()

            def conj(t):
                if isinstance(t, ast.BoolOp) and isinstance(t.op, ast.And):
                    for v in t.values:
                        conj(v)
                elif isinstance(t, ast.Compare) and len(t.ops) == 1 and \
                        isinstance(t.comparators[0], ast.Constant) and \
                        t.comparators[0].value is None:
                    k = _dotted_text(t.left)
                    if k:
                        if isinstance(t.ops[0], ast.IsNot):
                            pos.add(k)
                        elif isinstance(t.ops[0], ast.Is):
                            neg.add(k)
                elif isinstance(t, ast.Name):
                    pos.update(env_flag.get(t.id, frozenset()))
                    pos.add(t.id)          # `if obs:` truthiness guard
                elif isinstance(t, ast.Attribute):
                    k = _dotted_text(t)
                    if k:
                        pos.add(k)         # `if self.obs:` truthiness
                elif isinstance(t, ast.UnaryOp) and \
                        isinstance(t.op, ast.Not):
                    p2, n2 = guard_keys(t.operand)
                    pos.update(n2)
                    neg.update(p2)

            conj(test)
            return expand(pos), expand(neg)

        def check_expr(node, guarded: Set[str]):
            if node is None:
                return
            if isinstance(node, ast.IfExp):
                check_expr(node.test, guarded)
                pos, neg = guard_keys(node.test)
                check_expr(node.body, guarded | pos)
                check_expr(node.orelse, guarded | neg)
                return
            if isinstance(node, ast.BoolOp) and isinstance(node.op, ast.And):
                acc = set(guarded)
                for v in node.values:
                    check_expr(v, acc)
                    pos, _ = guard_keys(v)
                    acc |= pos
                return
            if isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) and \
                        f.attr.startswith(_SEAM_PREFIXES):
                    key = _plane_key(f.value)
                    cands: Set[str] = set()
                    if key is not None:
                        cands.add(key)
                        if isinstance(f.value, ast.Name) and \
                                f.value.id in env_alias:
                            cands.add(env_alias[f.value.id])
                    elif isinstance(f.value, ast.Name) and \
                            f.value.id in env_alias:
                        # alias escape hatch: `fo = self.router.fleet_obs`
                        # then `fo.on_x()` — the alias name is not
                        # plane-ish, the aliased target is
                        target = env_alias[f.value.id]
                        if target.rsplit(".", 1)[-1].lstrip("_") in _PLANES:
                            key = target
                            cands = {f.value.id, target}
                    if key is not None and not (cands & guarded):
                        out.append(_finding(
                            rule, ctx, node,
                            f"seam call {key}.{f.attr}() without an "
                            f"`is None` disarm guard on '{key}'"))
                check_expr(f.value if isinstance(f, ast.Attribute) else f,
                           guarded)
                for a in node.args:
                    check_expr(a, guarded)
                for k in node.keywords:
                    check_expr(k.value, guarded)
                return
            for child in ast.iter_child_nodes(node):
                check_expr(child, guarded)

        def terminates(stmts) -> bool:
            return bool(stmts) and isinstance(
                stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break))

        def scan(stmts, guarded: Set[str]):
            guarded = set(guarded)
            for st in stmts:
                if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                    continue
                if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                        and isinstance(st.targets[0], ast.Name):
                    check_expr(st.value, guarded)
                    name = st.targets[0].id
                    d = _dotted_text(st.value)
                    if d:
                        env_alias[name] = d
                    pos, _ = guard_keys(st.value)
                    pos.discard(name)
                    if pos:
                        env_flag[name] = frozenset(pos)
                elif isinstance(st, ast.If):
                    check_expr(st.test, guarded)
                    pos, neg = guard_keys(st.test)
                    scan(st.body, guarded | pos)
                    scan(st.orelse, guarded | neg)
                    if terminates(st.body):
                        guarded |= neg
                    if st.orelse and terminates(st.orelse):
                        guarded |= pos
                elif isinstance(st, ast.Assert):
                    pos, _ = guard_keys(st.test)
                    guarded |= pos
                elif isinstance(st, ast.While):
                    check_expr(st.test, guarded)
                    pos, _ = guard_keys(st.test)
                    scan(st.body, guarded | pos)
                    scan(st.orelse, guarded)
                elif isinstance(st, ast.For):
                    check_expr(st.iter, guarded)
                    scan(st.body, guarded)
                    scan(st.orelse, guarded)
                elif isinstance(st, ast.With):
                    for item in st.items:
                        check_expr(item.context_expr, guarded)
                    scan(st.body, guarded)
                elif isinstance(st, ast.Try):
                    scan(st.body, guarded)
                    for h in st.handlers:
                        scan(h.body, guarded)
                    scan(st.orelse, guarded)
                    scan(st.finalbody, guarded)
                else:
                    check_expr(st, guarded)

        scan(fn.body, set())
    return out


# =============================================================================
# CCY201 — the request lifecycle table
# =============================================================================
_STATE_CONSTS = {"WAITING": "waiting", "RUNNING": "running",
                 "FINISHED": "finished", "HANDOFF": "handoff"}


def _state_value(node) -> Optional[str]:
    if isinstance(node, ast.Name) and node.id in _STATE_CONSTS:
        return _STATE_CONSTS[node.id]
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


@_register(
    "CCY201", "illegal-state-transition",
    "request state assignment outside scheduler.REQUEST_TRANSITIONS, "
    "or a terminal finish/fail path without exactly one terminal "
    "trace event",
    "the request lifecycle is the literal table "
    "serving/scheduler.py REQUEST_TRANSITIONS ('new' -> waiting -> "
    "running/handoff -> finished): only declared edges may be "
    "assigned, and every function that terminally resolves a request "
    "(req.finish()/req.fail(...)) pairs each resolution with exactly "
    "one obs.on_finish/on_fail terminal trace event — zero loses the "
    "lifecycle's end, two double-counts SLO attainment.",
    framework_only=True)
def _check_state_machine(ctx: FileContext):
    if not _is_serving_path(ctx.path):
        return []
    rule = RULES["CCY201"]
    info = _info(ctx)
    table = load_request_transitions()
    enterable = {s for outs in table.values() for s in outs}
    out: List = []

    # classes owning the state machine itself (Request): their methods
    # ARE the mechanism, not a lifecycle path
    owner_classes = set()
    for cls in info.classes:
        for m in cls.body:
            if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and m.name == "__init__":
                for st in _own_body_walk(m):
                    if any(a == "state"
                           for _, a in _self_attr_writes(st)):
                        owner_classes.add(cls.name)

    for fn in info.functions:
        # -- part A: .state assignments must be declared edges --------
        # (_own_body_walk is stack-ordered; the prev-state edge check
        # needs source order)
        prev_by_target: Dict[str, str] = {}
        assigns = sorted(
            (n for n in _own_body_walk(fn) if isinstance(n, ast.Assign)),
            key=lambda n: (n.lineno, n.col_offset))
        for n in assigns:
            for t in n.targets:
                if not (isinstance(t, ast.Attribute) and
                        t.attr == "state"):
                    continue
                val = _state_value(n.value)
                if val is None:
                    continue        # dynamic / not a lifecycle state
                if val not in table:
                    out.append(_finding(
                        rule, ctx, n,
                        f"assigns unknown lifecycle state {val!r} "
                        f"(REQUEST_TRANSITIONS states: "
                        f"{sorted(s for s in table if s != 'new')})"))
                    continue
                tgt = _dotted_text(t.value) or "<req>"
                if fn.name == "__init__":
                    frm = "new"
                else:
                    frm = prev_by_target.get(tgt)
                if frm is not None and val not in table.get(frm, ()):
                    out.append(_finding(
                        rule, ctx, n,
                        f"state transition {frm!r} -> {val!r} is not in "
                        f"REQUEST_TRANSITIONS"))
                elif frm is None and val not in enterable:
                    out.append(_finding(
                        rule, ctx, n,
                        f"state {val!r} is not enterable by any "
                        f"REQUEST_TRANSITIONS edge"))
                prev_by_target[tgt] = val

        # -- part B: exactly one terminal trace event per resolution --
        if info.class_of.get(id(fn)) in owner_classes:
            continue
        resolutions: List[ast.Call] = []
        terminal_events = 0
        for n in _own_body_walk(fn):
            if not isinstance(n, ast.Call) or \
                    not isinstance(n.func, ast.Attribute):
                continue
            attr = n.func.attr
            base_is_self = isinstance(n.func.value, ast.Name) and \
                n.func.value.id == "self"
            if attr in ("on_finish", "on_fail"):
                terminal_events += 1
            elif not base_is_self and (
                    (attr == "finish" and not n.args) or
                    (attr == "fail" and n.args)):
                resolutions.append(n)
        if resolutions and terminal_events != len(resolutions):
            out.append(_finding(
                rule, ctx, resolutions[0],
                f"{len(resolutions)} terminal resolution(s) "
                f"(.finish()/.fail()) but {terminal_events} terminal "
                f"trace event(s) (on_finish/on_fail): the lifecycle "
                f"contract is exactly one per resolution"))
    return out
