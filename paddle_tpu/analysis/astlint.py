"""AST linter: run the rule registry over framework (and user) source.

Stdlib-only by design — no jax import, no paddle_tpu import — so the CI
driver lints a broken tree in well under the 30 s budget and editors can
call ``lint_source`` per keystroke.

Scope semantics: files under the ``paddle_tpu`` package are *framework*
files and get every rule; anything else (user scripts, examples, tests)
gets only the rules that encode portable invariants (version-shim
bypasses, exception hygiene). Rules may exempt specific path suffixes —
``utils/jax_compat.py`` is the one place allowed to spell raw JAX API.

Suppression: ``# tpu-lint: disable=TPU101`` (comma-separated ids) on the
offending line suppresses those findings for that line only. Unknown ids
in a disable comment are themselves reported (TPU000) — a suppression
that cannot mean anything is a typo hiding a real finding.
"""
from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Set

from .rules import Finding, FileContext, RULES

__all__ = ["lint_source", "lint_file", "lint_paths", "iter_python_files"]

_SUPPRESS_RE = re.compile(r"#\s*tpu-lint:\s*disable=([A-Za-z0-9_,\s]+)")


def _suppressions(source: str, path: str):
    """({line: set(ids)}, [TPU000 findings for unknown ids]).

    Tokenize-based: only real COMMENT tokens count, so lint fixtures and
    docs quoting the syntax inside string literals are not suppressions."""
    by_line: Dict[int, Set[str]] = {}
    bad: List[Finding] = []
    try:
        tokens = [(t.start[0], t.start[1], t.string) for t in
                  tokenize.generate_tokens(io.StringIO(source).readline)
                  if t.type == tokenize.COMMENT]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        tokens = []
    for line, col, text in tokens:
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        ids = {s.strip() for s in m.group(1).split(",") if s.strip()}
        unknown = sorted(ids - set(RULES))
        for u in unknown:
            bad.append(Finding(
                "TPU000", path, line, col,
                f"suppression names unknown rule {u!r}",
                "valid ids: " + ", ".join(sorted(RULES)), "error"))
        by_line[line] = by_line.get(line, set()) | (ids & set(RULES))
    return by_line, bad


def _is_framework_path(path: str) -> bool:
    norm = os.path.abspath(path).replace(os.sep, "/")
    return "/paddle_tpu/" in norm


def lint_source(source: str, path: str = "<string>",
                is_framework: Optional[bool] = None,
                rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Lint one source blob. ``rules`` restricts to the given ids."""
    if is_framework is None:
        is_framework = _is_framework_path(path)
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding("TPU000", path, e.lineno or 0, e.offset or 0,
                        f"syntax error: {e.msg}", "", "error")]
    ctx = FileContext(path, source, tree, is_framework)
    suppress, findings = _suppressions(source, path)
    norm = os.path.abspath(path).replace(os.sep, "/")
    for rule in RULES.values():
        if rules is not None and rule.id not in rules:
            continue
        if rule.framework_only and not is_framework:
            continue
        if any(norm.endswith(suf) for suf in rule.exempt_suffixes):
            continue
        for f in rule.check(ctx):
            if rule.id not in suppress.get(f.line, ()):
                findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def lint_file(path: str, **kw) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as f:
        return lint_source(f.read(), path, **kw)


_SKIP_DIRS = {"__pycache__", ".git", ".xla_cache", "build", "dist",
              "node_modules", ".venv"}


def iter_python_files(paths: Iterable[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs if d not in _SKIP_DIRS)
            for name in sorted(files):
                if name.endswith(".py"):
                    yield os.path.join(root, name)


def lint_paths(paths: Iterable[str],
               rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Lint every .py file under the given files/directories."""
    findings: List[Finding] = []
    for f in iter_python_files(paths):
        findings.extend(lint_file(f, rules=rules))
    return findings
