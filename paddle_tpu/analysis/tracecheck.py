"""Trace sanitizer: dynamic checks for the sharp bits AST linting cannot see.

``trace_check(fn, args)`` traces a step function the way ``jax.jit`` would
and reports the hazards that burn TPU pod-hours at runtime:

* **Recompile hazards** — Python scalars closed over by the function
  (baked into the trace as weak-typed constants: every rebuilt closure
  retraces and recompiles), Python branches on traced values, and traced
  values forced into static positions (shapes, range bounds). An
  empirical retrace probe also jits the function twice with perturbed
  same-shape inputs and flags compile-cache growth.
* **Host round-trips** — ``.item()`` / ``float()`` / implicit numpy
  conversion inside the step: each one is a device->host sync that
  serializes the pipeline.
* **Donated-buffer misuse** — ``donate_argnums`` entries whose shape and
  dtype match no output, so XLA silently drops the donation (the memory
  saving the caller is counting on never happens).

``check_collective_schedules`` is the cross-rank half: given per-rank
collective sequences recorded by ``analysis.schedule`` (hooked into
``distributed/communication.py``, ``host_collectives.py`` and
``store.barrier``), it reports the first point where ranks disagree on
which collective comes next — the divergent/deadlocking schedule bug —
and count mismatches where some ranks keep issuing collectives after
others stopped.

Findings reuse the linter's ``Finding`` shape so ``tools/lint.py`` can
report both passes uniformly.
"""
from __future__ import annotations

import inspect
import traceback
from typing import Dict, List, Mapping, Optional, Sequence, Tuple, Union

import jax

from .rules import Finding

__all__ = ["trace_check", "check_collective_schedules", "TRACE_RULES"]

# id -> (name, hint) — mirrored in tools/lint.py --fix-hints and README
TRACE_RULES = {
    "TRC101": ("scalar-closure",
               "pass the value as a traced argument (or fold it into the "
               "pytree of parameters) instead of closing over it — every "
               "closure rebuild bakes a new weak-typed constant and "
               "recompiles"),
    "TRC102": ("python-branch-on-tracer",
               "replace Python `if`/`int()` on traced values with "
               "jnp.where / lax.cond / lax.switch, or hoist the decision "
               "out of the jitted region as a static argument"),
    "TRC103": ("host-sync-in-step",
               "keep .item()/float()/np.asarray() out of the step "
               "function; return the value and read it outside jit (or "
               "log asynchronously every N steps)"),
    "TRC104": ("donation-unused",
               "donate only buffers an output can alias (same shape and "
               "dtype, e.g. params -> new params); XLA silently ignores "
               "unusable donations and the expected memory saving never "
               "happens"),
    "TRC105": ("retrace-on-same-shapes",
               "the function retraced on a second call with identical "
               "shapes/dtypes — hunt for value-dependent Python control "
               "flow, fresh closures, or non-array arguments changing "
               "between calls"),
    "TRC201": ("collective-order-divergence",
               "all ranks must issue the same collective sequence; gate "
               "rank-dependent work so it cannot reorder or skip "
               "collectives (e.g. coordinator-only code must not call "
               "collectives other ranks do not)"),
    "TRC202": ("collective-count-mismatch",
               "some ranks issue more collectives than others — the "
               "extras will block forever; make every rank run the same "
               "number of rounds (loop bounds and early exits must be "
               "rank-invariant)"),
}


def _f(rule: str, where: str, line: int, message: str,
       severity: str = "error") -> Finding:
    name, hint = TRACE_RULES[rule]
    return Finding(rule, where, line, 0, message, hint, severity)


# -- Tensor <-> array plumbing (duck-typed: no framework import needed) -------
def _is_tensor(x) -> bool:
    return type(x).__name__ == "Tensor" and hasattr(x, "_data")


def _unwrap(x):
    if _is_tensor(x):
        return x._data
    if isinstance(x, (list, tuple)):
        return type(x)(_unwrap(v) for v in x)
    if isinstance(x, dict):
        return {k: _unwrap(v) for k, v in x.items()}
    return x


def _rewrap_like(template, x):
    if _is_tensor(template):
        return type(template)(x)
    if isinstance(template, (list, tuple)):
        return type(template)(_rewrap_like(t, v)
                              for t, v in zip(template, x))
    if isinstance(template, dict):
        return {k: _rewrap_like(template[k], x[k]) for k in template}
    return x


def _perturb_scalars(x):
    """Same structure, same avals, different Python-scalar values — what a
    second training step looks like to the compile cache."""
    if isinstance(x, bool):
        return x
    if isinstance(x, int):
        return x + 1
    if isinstance(x, float):
        return x + 1.0
    if isinstance(x, (list, tuple)):
        return type(x)(_perturb_scalars(v) for v in x)
    if isinstance(x, dict):
        return {k: _perturb_scalars(v) for k, v in x.items()}
    return x


def _fn_label(fn) -> str:
    return getattr(fn, "__qualname__", None) or getattr(
        fn, "__name__", None) or repr(fn)


def _user_line(fn, exc) -> int:
    """Best-effort source line of `fn` where the trace blew up."""
    try:
        src_file = inspect.getsourcefile(fn)
    except TypeError:
        src_file = None
    line = 0
    for frame in traceback.extract_tb(exc.__traceback__):
        if src_file and frame.filename == src_file:
            line = frame.lineno or line
    if not line:
        try:
            line = inspect.getsourcelines(fn)[1]
        except (OSError, TypeError):
            line = 0
    return line


def _scalar_closures(fn) -> List[Tuple[str, object]]:
    try:
        cv = inspect.getclosurevars(fn)
    except TypeError:
        return []
    return [(name, val) for name, val in sorted(cv.nonlocals.items())
            if isinstance(val, (bool, int, float))]


def _leaf_avals(tree) -> List[Tuple[Tuple[int, ...], str]]:
    out = []
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            out.append((tuple(leaf.shape), str(leaf.dtype)))
        elif isinstance(leaf, (bool, int, float, complex)):
            out.append(((), type(leaf).__name__))
    return out


def trace_check(fn, args: Sequence = (), kwargs: Optional[dict] = None,
                *, donate_argnums: Sequence[int] = (),
                label: Optional[str] = None,
                check_retrace: bool = True) -> List[Finding]:
    """Trace `fn(*args, **kwargs)` and report TPU sharp bits as findings.

    ``check_retrace=True`` additionally jits and RUNS the function twice
    (second time with perturbed Python-scalar values), so pass example
    args that are cheap to execute.
    """
    kwargs = dict(kwargs or {})
    where = label or _fn_label(fn)
    findings: List[Finding] = []

    for name, val in _scalar_closures(fn):
        findings.append(_f(
            "TRC101", where, 0,
            f"closes over Python scalar {name}={val!r}: baked into the "
            "trace as a weak-typed constant — a rebuilt closure with a "
            "new value recompiles"))

    arr_args = _unwrap(list(args))
    arr_kwargs = _unwrap(kwargs)

    def wrapped(*a, **k):
        out = fn(*_rewrap_like(list(args), list(a)),
                 **_rewrap_like(kwargs, k))
        return _unwrap(out)

    bool_err = getattr(jax.errors, "TracerBoolConversionError", ())
    int_err = getattr(jax.errors, "TracerIntegerConversionError", ())
    arr_err = getattr(jax.errors, "TracerArrayConversionError", ())
    conc_err = jax.errors.ConcretizationTypeError
    closed = None
    try:
        closed = jax.make_jaxpr(wrapped)(*arr_args, **arr_kwargs)
    except bool_err as e:
        findings.append(_f("TRC102", where, _user_line(fn, e),
                           "Python branch on a traced value (if/while on "
                           "tracer): the branch cannot be staged and "
                           "value-dependent variants each retrace"))
    except int_err as e:
        findings.append(_f("TRC102", where, _user_line(fn, e),
                           "traced value forced to a Python int (shape/"
                           "index/range position): every distinct value "
                           "would need its own compile"))
    except arr_err as e:
        findings.append(_f("TRC103", where, _user_line(fn, e),
                           "implicit device->host conversion of a traced "
                           "value (np.asarray/np.float64-style): a sync "
                           "inside the step"))
    except conc_err as e:
        # the generic concretization error covers both host conversions
        # (float()/bool()/.item()) and traced values forced into static
        # shape/size positions (jnp.arange bound, reshape dim via int());
        # JAX names the offending function in the message
        msg = str(e)
        if any(s in msg for s in ("`float` function", "`bool` function",
                                  "item() method", "tolist", "numpy")):
            findings.append(_f(
                "TRC103", where, _user_line(fn, e),
                ".item()/float()/bool() on a traced value: a "
                "device->host round-trip inside the step"))
        else:
            findings.append(_f(
                "TRC102", where, _user_line(fn, e),
                "traced value used in a static (shape/size) position: "
                "every distinct value would need its own compile"))

    if closed is not None and donate_argnums:
        out_avals = _leaf_avals([getattr(v, "aval", v)
                                 for v in closed.jaxpr.outvars])
        budget: Dict[Tuple, int] = {}
        for aval in out_avals:
            budget[aval] = budget.get(aval, 0) + 1
        for i in donate_argnums:
            if i >= len(args):
                continue
            for aval in _leaf_avals(arr_args[i]):
                if budget.get(aval, 0) > 0:
                    budget[aval] -= 1
                else:
                    shape, dtype = aval
                    findings.append(_f(
                        "TRC104", where, 0,
                        f"donated arg {i} has a {dtype}{list(shape)} "
                        "buffer no output can reuse: XLA drops the "
                        "donation silently"))

    if closed is not None and check_retrace:
        jitted = jax.jit(wrapped)
        cache_size = getattr(jitted, "_cache_size", None)
        if callable(cache_size):
            try:
                jitted(*arr_args, **arr_kwargs)
                n1 = cache_size()
                jitted(*_perturb_scalars(arr_args),
                       **_perturb_scalars(arr_kwargs))
                n2 = cache_size()
            except Exception:  # execution failure ≠ a trace hazard
                n1 = n2 = 0
            if n2 > n1:
                findings.append(_f(
                    "TRC105", where, 0,
                    "retraced on a second call with identical shapes and "
                    "dtypes: the step will recompile every iteration"))

    return findings


# -- cross-rank collective order ----------------------------------------------
Event = Union[str, Tuple[str, str]]


def _render(ev: Event) -> str:
    if isinstance(ev, str):
        return ev
    op, detail = ev
    return f"{op}({detail})" if detail else op


def _group(d: Mapping[int, str]) -> str:
    """'ranks [0, 2]: all_reduce | rank [1]: barrier' — grouped by op."""
    by_op: Dict[str, List[int]] = {}
    for rank, op in sorted(d.items()):
        by_op.setdefault(op, []).append(rank)
    return " | ".join(f"rank{'s' if len(r) > 1 else ''} {r}: {op}"
                      for op, r in sorted(by_op.items(),
                                          key=lambda kv: kv[1]))


def check_collective_schedules(
        schedules: Mapping[int, Sequence[Event]]) -> List[Finding]:
    """Compare per-rank collective sequences; report the first divergence.

    `schedules`: {rank: sequence of events}, each event an op string or an
    (op, detail) tuple — the shapes ``analysis.schedule`` records and
    ``load_schedules`` returns. Returns [] when every rank agrees.
    """
    if len(schedules) < 2:
        return []
    rendered = {r: [_render(e) for e in evs]
                for r, evs in schedules.items()}
    where = "<collective-schedule>"
    n_max = max(len(v) for v in rendered.values())
    for i in range(n_max):
        present = {r: evs[i] for r, evs in rendered.items()
                   if i < len(evs)}
        done = sorted(set(rendered) - set(present))
        if done:
            return [_f(
                "TRC202", where, i + 1,
                f"collective count mismatch at event {i + 1}: "
                f"rank{'s' if len(done) > 1 else ''} {done} recorded no "
                f"more events while {_group(present)} — the extra "
                "collective(s) will wait forever")]
        if len(set(present.values())) > 1:
            return [_f(
                "TRC201", where, i + 1,
                f"collective schedules diverge at event {i + 1}: "
                f"{_group(present)} — ranks posting different "
                "collectives deadlock")]
    return []
