"""SHD1xx — static sharding/layout rules (the shardcheck AST half).

Distributed layout is the first-class programming surface of a
TPU-native framework: a typo'd mesh axis, a duplicated PartitionSpec
entry, or a collective over an axis the enclosing manual region never
bound all COMPILE fine and only surface as a hang, a wrong result, or a
10x step-time regression once a pod is burning. These rules catch the
machine-checkable subset before any device is touched.

Ground truth is the canonical axis registry ``distributed/mesh.py
KNOWN_AXES``, read statically with ``ast.literal_eval`` (the same
no-jax-at-lint-time contract as the chaos-site and metric-catalog
rules). The abstract layout evaluator (divisibility, implicit-reshard
cost — SHD2xx) lives in ``analysis/shardcheck.py``; this module is the
stdlib-only half that rides the astlint rule framework, so SHD findings
get stable ids, severities, fix hints, baseline keys, and
``# tpu-lint: disable=`` suppression for free.

Registered into ``rules.RULES`` on import (rules.py imports this module
at the bottom of its own body).
"""
from __future__ import annotations

import ast
import functools
import os
from typing import Dict, List, Optional, Tuple

from .rules import (RULES, FileContext, _finding, _literal_from_source,
                    _PKG_ROOT, _register)

__all__ = ["load_known_axes"]


@functools.lru_cache(maxsize=1)
def _known_axes_cached() -> Tuple[str, ...]:
    path = os.path.join(_PKG_ROOT, "distributed", "mesh.py")
    return tuple(_literal_from_source(path, "KNOWN_AXES"))


def load_known_axes() -> Tuple[str, ...]:
    """Canonical mesh-axis names, read statically from
    distributed/mesh.py's KNOWN_AXES registry (registry order)."""
    return _known_axes_cached()


# -- literal harvesting helpers -----------------------------------------------
def _str_const(node) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _is_partition_spec_call(ctx: FileContext, node) -> bool:
    if not isinstance(node, ast.Call):
        return False
    d = ctx.dotted(node.func)
    return bool(d) and (d == "PartitionSpec" or d.endswith(".PartitionSpec")
                        or d.endswith("PartitionSpec"))


def _spec_axis_literals(call: ast.Call) -> List[Tuple[ast.AST, str]]:
    """(node, axis-name) for every string literal in a PartitionSpec
    call: plain entries, tuple entries, and constants inside starred
    expressions (``PartitionSpec(*(["pp"] + [None] * k))``)."""
    out: List[Tuple[ast.AST, str]] = []
    for arg in call.args:
        if isinstance(arg, ast.Starred):
            for n in ast.walk(arg.value):
                if (s := _str_const(n)) is not None:
                    out.append((n, s))
        elif isinstance(arg, (ast.Tuple, ast.List)):
            for elt in arg.elts:
                if (s := _str_const(elt)) is not None:
                    out.append((elt, s))
        elif (s := _str_const(arg)) is not None:
            out.append((arg, s))
    return out


# axis-name positional index per lax-style collective / axis query
_COLLECTIVE_AXIS_ARG = {
    "psum": 1, "pmax": 1, "pmin": 1, "pmean": 1, "ppermute": 1,
    "all_gather": 1, "all_to_all": 1, "psum_scatter": 1,
    "axis_index": 0, "axis_size": 0,
}
# only these heads make a tail above a collective (communication.py's
# eager all_gather(tensor_list, tensor) takes no axis-name string)
_COLLECTIVE_HEADS = ("jax.lax", "lax", "jax_compat")


def _collective_axis_literal(ctx: FileContext,
                             call: ast.Call) -> Optional[Tuple[ast.AST, str]]:
    """(node, axis) when `call` is a lax/jax_compat collective with a
    literal axis-name argument (positional or axis_name= keyword)."""
    d = ctx.dotted(call.func)
    if not d:
        return None
    head, _, tail = d.rpartition(".")
    if tail not in _COLLECTIVE_AXIS_ARG:
        return None
    if head and not head.endswith(_COLLECTIVE_HEADS):
        return None
    if not head and ("jax_compat" not in ctx.imports.get(tail, "")
                     and "lax" not in ctx.imports.get(tail, "")):
        return None
    idx = _COLLECTIVE_AXIS_ARG[tail]
    if len(call.args) > idx and (s := _str_const(call.args[idx])) is not None:
        return call.args[idx], s
    for kw in call.keywords:
        if kw.arg == "axis_name" and (s := _str_const(kw.value)) is not None:
            return kw.value, s
    return None


def _axis_kwarg_literals(call: ast.Call) -> List[Tuple[ast.AST, str]]:
    """Literal axis names in axis_name=/seq_axis=/ep_axis= keywords and
    axis_names={...} set literals of any call."""
    out = []
    for kw in call.keywords:
        if kw.arg in ("axis_name", "seq_axis", "ep_axis"):
            if (s := _str_const(kw.value)) is not None:
                out.append((kw.value, s))
        elif kw.arg == "axis_names" and isinstance(kw.value,
                                                  (ast.Set, ast.Tuple,
                                                   ast.List)):
            for elt in kw.value.elts:
                if (s := _str_const(elt)) is not None:
                    out.append((elt, s))
    return out


def _is_shard_map_call(ctx: FileContext, node) -> bool:
    if not isinstance(node, ast.Call):
        return False
    d = ctx.dotted(node.func)
    return bool(d) and d.rpartition(".")[2] == "shard_map"


def _keyword(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


# =============================================================================
# SHD101 — unknown mesh axis
# =============================================================================
@_register(
    "SHD101", "unknown-mesh-axis",
    "string axis name in a PartitionSpec / collective / axis-name "
    "argument that no framework mesh defines",
    "mesh axes are the canonical registry distributed.mesh.KNOWN_AXES "
    "(dp/pp/sep/sharding/ep/mp); a typo'd axis compiles and then hangs "
    "or silently replicates on real hardware — fix the name or add the "
    "axis to KNOWN_AXES",
    framework_only=True)
def _check_unknown_axis(ctx: FileContext):
    rule = RULES["SHD101"]
    try:
        known = set(load_known_axes())
    except (OSError, LookupError):
        return
    seen_nodes = set()

    def emit(node, axis, where):
        if id(node) in seen_nodes or axis in known:
            return
        seen_nodes.add(id(node))
        yield _finding(rule, ctx, node,
                       f"axis {axis!r} in {where} is not in "
                       "distributed.mesh.KNOWN_AXES")

    for node in ctx.nodes():
        if _is_partition_spec_call(ctx, node):
            for n, axis in _spec_axis_literals(node):
                yield from emit(n, axis, "a PartitionSpec")
        if isinstance(node, ast.Call):
            hit = _collective_axis_literal(ctx, node)
            if hit is not None:
                yield from emit(hit[0], hit[1], "a collective axis arg")
            for n, axis in _axis_kwarg_literals(node):
                yield from emit(n, axis, "an axis-name keyword")
            d = ctx.dotted(node.func) or ""
            tail = d.rpartition(".")[2]
            if tail == "get_dim_size" and node.args and \
                    (s := _str_const(node.args[0])) is not None:
                yield from emit(node.args[0], s, "get_dim_size()")
            elif tail == "annotate_param" and len(node.args) > 1 and \
                    (s := _str_const(node.args[1])) is not None:
                yield from emit(node.args[1], s, "annotate_param()")
            elif tail == "sharding_constraint" and len(node.args) > 1:
                for a in node.args[1:]:
                    if (s := _str_const(a)) is not None:
                        yield from emit(a, s, "sharding_constraint()")
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            params = node.args.posonlyargs + node.args.args \
                + node.args.kwonlyargs
            defaults = ([None] * (len(node.args.posonlyargs)
                                  + len(node.args.args)
                                  - len(node.args.defaults))
                        + list(node.args.defaults)
                        + list(node.args.kw_defaults))
            for p, dflt in zip(params, defaults):
                if dflt is not None and (p.arg.endswith("_axis")
                                         or p.arg == "axis_name") and \
                        (s := _str_const(dflt)) is not None:
                    yield from emit(dflt, s,
                                    f"the default of parameter {p.arg!r}")


# =============================================================================
# SHD102 — duplicate axis within one PartitionSpec
# =============================================================================
@_register(
    "SHD102", "duplicate-spec-axis",
    "the same mesh axis appears in two entries of one PartitionSpec",
    "a dimension set cannot be sharded over one mesh axis twice — jax "
    "rejects it at trace time at best, and at worst the spec silently "
    "means something else after a refactor; drop one entry",
    framework_only=True)
def _check_duplicate_spec_axis(ctx: FileContext):
    rule = RULES["SHD102"]
    for node in ctx.nodes():
        if not _is_partition_spec_call(ctx, node):
            continue
        counts: Dict[str, List[ast.AST]] = {}
        for n, axis in _spec_axis_literals(node):
            counts.setdefault(axis, []).append(n)
        for axis, nodes in counts.items():
            if len(nodes) > 1:
                yield _finding(rule, ctx, nodes[1],
                               f"axis {axis!r} appears {len(nodes)}x in one "
                               "PartitionSpec")


# =============================================================================
# SHD103 — collective over an axis absent from the enclosing manual region
# =============================================================================
def _region_axes(ctx: FileContext) -> set:
    """Mesh axes this file's manual regions bind: every literal axis in
    a PartitionSpec, every axis_names={...} entry, every axis_name=
    keyword binding (functools.partial wiring included). A collective's
    OWN axis argument does not bind anything — counting it would make
    every kwarg-spelled violation self-justifying."""
    axes = set()
    for node in ctx.nodes():
        if _is_partition_spec_call(ctx, node):
            axes.update(a for _, a in _spec_axis_literals(node))
        if isinstance(node, ast.Call) and \
                _collective_axis_literal(ctx, node) is None:
            axes.update(a for _, a in _axis_kwarg_literals(node))
    return axes


@_register(
    "SHD103", "collective-axis-outside-region",
    "collective over a literal axis that no shard_map region in this "
    "file binds (specs / axis_names never mention it)",
    "a collective over an axis the enclosing mesh region does not bind "
    "is an unbound-axis-name trace error at best and a cross-region "
    "deadlock at worst; thread the axis through the region's in_specs/"
    "axis_names (or take it as the body's axis_name parameter)",
    framework_only=True)
def _check_collective_outside_region(ctx: FileContext):
    rule = RULES["SHD103"]
    try:
        known = set(load_known_axes())
    except (OSError, LookupError):
        return
    if not any(_is_shard_map_call(ctx, n) for n in ctx.nodes()):
        return  # no manual region here: nothing to check against
    bound = _region_axes(ctx)
    for node in ctx.nodes():
        if not isinstance(node, ast.Call):
            continue
        hit = _collective_axis_literal(ctx, node)
        if hit is None:
            continue
        n, axis = hit
        if axis in known and axis not in bound:
            yield _finding(
                rule, ctx, n,
                f"collective over axis {axis!r}, but this file's "
                f"shard_map regions only bind {sorted(bound) or 'nothing'}")


# =============================================================================
# SHD104 — in_specs arity vs wrapped function signature
# =============================================================================
def _positional_arity(fn) -> Optional[int]:
    """Number of call-time positional params of a def/lambda; None when
    *args makes it unbounded."""
    a = fn.args
    if a.vararg is not None:
        return None
    return len(a.posonlyargs) + len(a.args)


def _resolve_callee(ctx: FileContext, node):
    """Resolve a shard_map first argument to (def-or-lambda node,
    n_bound_positional, bound_kw_names) or None. Handles direct lambdas,
    file-level defs, functools.partial over a def, and simple
    ``name = partial(...)`` / ``name = lambda ...`` local assignments."""
    if isinstance(node, ast.Lambda):
        return node, 0, set()
    if isinstance(node, ast.Call):
        d = ctx.dotted(node.func) or ""
        if d.rpartition(".")[2] != "partial" or not node.args:
            return None
        inner = _resolve_callee(ctx, node.args[0])
        if inner is None:
            return None
        fn, bound_pos, bound_kw = inner
        return (fn, bound_pos + len(node.args) - 1,
                bound_kw | {kw.arg for kw in node.keywords if kw.arg})
    if not isinstance(node, ast.Name):
        return None
    # last simple assignment to that name wins; a def by that name too
    defs = [n for n in ctx.functions()
            if getattr(n, "name", None) == node.id]
    assigns = [n.value for n in ctx.nodes()
               if isinstance(n, ast.Assign) and len(n.targets) == 1
               and isinstance(n.targets[0], ast.Name)
               and n.targets[0].id == node.id]
    if len(defs) + len(assigns) != 1:
        return None  # ambiguous or imported: stay silent
    if defs:
        return defs[0], 0, set()
    return _resolve_callee(ctx, assigns[0])


@_register(
    "SHD104", "spec-arity-mismatch",
    "shard_map in_specs tuple length differs from the wrapped "
    "function's positional arity",
    "in_specs must give one spec per call-time positional argument of "
    "the wrapped body; an arity mismatch is a tree-structure error at "
    "trace time on one jax version and silently zips short on another",
    framework_only=True)
def _check_spec_arity(ctx: FileContext):
    rule = RULES["SHD104"]
    for node in ctx.nodes():
        if not _is_shard_map_call(ctx, node) or not node.args:
            continue
        in_specs = _keyword(node, "in_specs")
        if not isinstance(in_specs, ast.Tuple):
            continue
        resolved = _resolve_callee(ctx, node.args[0])
        if resolved is None:
            continue
        fn, bound_pos, bound_kw = resolved
        arity = _positional_arity(fn)
        if arity is None:
            continue
        pos_names = [a.arg for a in fn.args.posonlyargs + fn.args.args]
        required = arity - bound_pos - len(bound_kw & set(pos_names))
        n_specs = len(in_specs.elts)
        if required >= 0 and n_specs != required:
            name = getattr(fn, "name", "<lambda>")
            yield _finding(
                rule, ctx, node,
                f"in_specs has {n_specs} entr{'y' if n_specs == 1 else 'ies'}"
                f" but {name}() takes {required} positional argument"
                f"{'' if required == 1 else 's'}")


# =============================================================================
# SHD105 — hard-coded mesh facts that the registry owns
# =============================================================================
_SIZE_LOOKUPS = {"get_dim_size", "axis_size"}


def _is_size_lookup(ctx: FileContext, node) -> bool:
    if not isinstance(node, ast.Call):
        return False
    d = ctx.dotted(node.func) or ""
    return d.rpartition(".")[2] in _SIZE_LOOKUPS


def _int_const_ge2(node) -> Optional[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool) and node.value >= 2:
        return node.value
    return None


def _canonical_restatement(known: Tuple[str, ...], strings: List[str]) -> bool:
    """True when `strings` restate the registry: >=3 entries, all known
    axes, in the registry's relative order (a deliberately different
    order — e.g. a topology build order — is NOT a restatement)."""
    if len(strings) < 3 or len(set(strings)) != len(strings):
        return False
    if not all(s in known for s in strings):
        return False
    idx = [known.index(s) for s in strings]
    return idx == sorted(idx)


@_register(
    "SHD105", "hard-coded-mesh-fact",
    "mesh fact the registry owns is hard-coded: an axis-name list "
    "restating distributed.mesh.KNOWN_AXES, or an axis size compared/"
    "reduced against an int literal",
    "derive names from the registry (e.g. `list(KNOWN_AXES)` or a "
    "filtered comprehension over it) and sizes from the mesh "
    "(`mesh.get_dim_size(axis)` / `axis_size(axis)`) — a literal copy "
    "drifts silently when the topology changes and the mesh registry "
    "does not",
    framework_only=True,
    exempt_suffixes=("distributed/mesh.py",))
def _check_hardcoded_mesh_fact(ctx: FileContext):
    rule = RULES["SHD105"]
    try:
        known = load_known_axes()
    except (OSError, LookupError):
        return
    for node in ctx.nodes():
        if isinstance(node, (ast.List, ast.Tuple)):
            strings = [s for e in node.elts
                       if (s := _str_const(e)) is not None]
            if len(strings) == len(node.elts) and \
                    _canonical_restatement(known, strings):
                yield _finding(
                    rule, ctx, node,
                    f"axis-name literal {strings} restates "
                    "distributed.mesh.KNOWN_AXES")
        elif isinstance(node, ast.Dict):
            keys = [s for k in node.keys
                    if k is not None and (s := _str_const(k)) is not None]
            if len(keys) == len(node.keys) and \
                    _canonical_restatement(known, keys):
                yield _finding(
                    rule, ctx, node,
                    f"mesh-axis dict keys {keys} restate "
                    "distributed.mesh.KNOWN_AXES")
        elif isinstance(node, ast.Compare) and len(node.ops) == 1 and \
                isinstance(node.ops[0], (ast.Eq, ast.NotEq)):
            sides = (node.left, node.comparators[0])
            for a, b in (sides, sides[::-1]):
                if _is_size_lookup(ctx, a) and \
                        (v := _int_const_ge2(b)) is not None:
                    yield _finding(
                        rule, ctx, node,
                        f"axis size compared against hard-coded literal {v}")
                    break
        elif isinstance(node, ast.BinOp) and \
                isinstance(node.op, (ast.Mod, ast.FloorDiv)):
            sides = (node.left, node.right)
            for a, b in (sides, sides[::-1]):
                if _is_size_lookup(ctx, a) and \
                        (v := _int_const_ge2(b)) is not None:
                    yield _finding(
                        rule, ctx, node,
                        f"axis size combined with hard-coded literal {v}")
                    break


# =============================================================================
# SHD106 — donated argument whose spec no output spec matches
# =============================================================================
def _spec_repr(node) -> Optional[str]:
    """Canonical text of a literal sharding expression (for structural
    equality); None when the expression is not statically renderable."""
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - malformed synthetic nodes
        return None


@_register(
    "SHD106", "donation-spec-unmatched",
    "jit donate_argnums names an argument whose in_sharding matches no "
    "out_sharding — XLA silently drops the donation",
    "donation only helps when an output can alias the donated buffer, "
    "which requires matching shardings; align the specs or drop the "
    "argnum (tracecheck TRC104 is the dynamic twin of this rule)",
    framework_only=True)
def _check_donation_spec(ctx: FileContext):
    rule = RULES["SHD106"]
    for node in ctx.nodes():
        if not isinstance(node, ast.Call):
            continue
        d = ctx.dotted(node.func) or ""
        if d.rpartition(".")[2] != "jit":
            continue
        donate = _keyword(node, "donate_argnums")
        in_sh = _keyword(node, "in_shardings")
        out_sh = _keyword(node, "out_shardings")
        if donate is None or not isinstance(in_sh, ast.Tuple) or \
                out_sh is None:
            continue
        if isinstance(donate, ast.Tuple):
            argnums = [e.value for e in donate.elts
                       if isinstance(e, ast.Constant)
                       and isinstance(e.value, int)]
        elif isinstance(donate, ast.Constant) and \
                isinstance(donate.value, int):
            argnums = [donate.value]
        else:
            continue
        outs = out_sh.elts if isinstance(out_sh, ast.Tuple) else [out_sh]
        out_reprs = {r for o in outs if (r := _spec_repr(o)) is not None}
        if not out_reprs:
            continue
        for i in argnums:
            if not 0 <= i < len(in_sh.elts):
                continue
            r = _spec_repr(in_sh.elts[i])
            if r is not None and r not in out_reprs:
                yield _finding(
                    rule, ctx, node,
                    f"donated arg {i} has in_sharding {r} but no "
                    "out_sharding matches it")
