"""Per-rank collective-schedule recording for the cross-rank order checker.

Cross-rank collective divergence (rank 0 enters an all_reduce while rank 1
sits in a barrier) is the classic whole-pod-hour bug: nothing crashes, the
job just stops. The store-routed host collectives and the compiled-path
entry points in ``distributed/communication.py`` already funnel through a
handful of choke points; this module gives those choke points one cheap
hook (a single list-index check when disabled, exactly like the chaos
probes) that appends ``(op, detail)`` events to a per-rank log.

Arm it programmatically (``start_recording()``) or via env
(``PADDLE_SCHEDULE_LOG=<dir>``) so a launcher can capture a whole
multi-process run without code changes: each rank appends JSONL to
``<dir>/schedule_rank<k>.jsonl``, line-flushed so a deadlocked or killed
rank still leaves its prefix on disk — which is precisely the evidence the
checker (``analysis.tracecheck.check_collective_schedules``) needs.

Stdlib-only: importable from the distributed layer without cycles.
"""
from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

__all__ = ["ScheduleRecorder", "start_recording", "stop_recording",
           "recording", "record", "load_schedules"]


class ScheduleRecorder:
    """Records collective events for one rank; optionally mirrors each
    event to a line-flushed JSONL file (truncated per run — stale events
    from a previous run would read as bogus divergence).

    keep_in_memory=False drops the in-process list (the env-armed
    whole-run capture writes potentially millions of events that only
    the file consumer reads — an unbounded list would leak for days)."""

    def __init__(self, rank: int = 0, path: Optional[str] = None,
                 keep_in_memory: bool = True):
        self.rank = int(rank)
        self.path = path
        self.keep_in_memory = keep_in_memory
        self.events: List[Tuple[str, str]] = []
        self._fh = open(path, "w", buffering=1) if path else None

    def record(self, op: str, detail: str = "") -> None:
        if self.keep_in_memory:
            self.events.append((op, detail))
        if self._fh is not None:
            self._fh.write(json.dumps({"op": op, "detail": detail}) + "\n")

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


# hot-path cell: call sites check `_REC[0] is not None` and nothing else
_REC: List[Optional[ScheduleRecorder]] = [None]


def start_recording(rank: int = 0, path: Optional[str] = None,
                    keep_in_memory: bool = True) -> ScheduleRecorder:
    rec = ScheduleRecorder(rank, path, keep_in_memory=keep_in_memory)
    _REC[0] = rec
    return rec


def stop_recording() -> List[Tuple[str, str]]:
    """Disarm and return the recorded events."""
    rec, _REC[0] = _REC[0], None
    if rec is None:
        return []
    rec.close()
    return rec.events


def recording() -> bool:
    return _REC[0] is not None


def record(op: str, detail: str = "") -> None:
    """Instrumented-call-site hook (no-op unless armed)."""
    rec = _REC[0]
    if rec is not None:
        rec.record(op, detail)


def load_schedules(directory: str) -> Dict[int, List[Tuple[str, str]]]:
    """{rank: [(op, detail)]} from a directory of per-rank JSONL logs."""
    out: Dict[int, List[Tuple[str, str]]] = {}
    for name in sorted(os.listdir(directory)):
        if not (name.startswith("schedule_rank") and
                name.endswith(".jsonl")):
            continue
        rank = int(name[len("schedule_rank"):-len(".jsonl")])
        events = []
        with open(os.path.join(directory, name)) as f:
            for line in f:
                line = line.strip()
                if line:
                    d = json.loads(line)
                    events.append((d["op"], d.get("detail", "")))
        out[rank] = events
    return out


# env-armed recording so a launcher can capture an unmodified script
_log_dir = os.environ.get("PADDLE_SCHEDULE_LOG", "").strip()
if _log_dir:
    os.makedirs(_log_dir, exist_ok=True)
    _rank = int(os.environ.get("PADDLE_TRAINER_ID",
                               os.environ.get("RANK", "0")) or 0)
    start_recording(_rank, os.path.join(_log_dir,
                                        f"schedule_rank{_rank}.jsonl"),
                    keep_in_memory=False)
