"""SHD2xx — abstract layout evaluator (the shardcheck dynamic half).

Runs a step function abstractly (``jax.make_jaxpr`` over
``ShapeDtypeStruct`` inputs — the same shapes-only abstract
interpretation as ``jax.eval_shape``, no devices, CPU-safe with
``JAX_PLATFORMS=cpu`` and no TPU present) and propagates a simple
per-dimension layout through the jaxpr:

* **SHD201** — divisibility: a dimension sharded over mesh axes whose
  product does not divide it means per-device padding and, on shape
  drift, a recompile per distinct remainder.
* **SHD202** — implicit-reshard hotspot: an op boundary whose incoming
  layouts force the compiler to materialize data movement (all-gather
  of a sharded contracting dim, psum of a reduced sharded dim, a
  layout conflict between elementwise operands, an output constraint
  the propagated layout cannot meet) with estimated traffic above a
  threshold. The byte numbers are a *model*, not a profile — they rank
  boundaries, they do not predict wall-clock.
* **SHD210** — layout-report drift: the stable subset of the report for
  the driver's representative step differs from the committed baseline
  (``tools/layout_baseline.json``); rerun ``tools/lint.py
  --update-baseline`` after an intentional layout change.

The full per-op report (``layout_report``) is machine-readable JSON:
one record per jaxpr equation with the op name, output shape, the
propagated spec, and the estimated reshard bytes — dump it with
``tools/lint.py --layout-report out.json`` for offline inspection.

jax imports live inside functions: importing this module stays
stdlib-cheap so ``tools/lint.py --fix-hints`` can print SHARD_RULES
without jax installed.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

from .rules import Finding

__all__ = ["SHARD_RULES", "layout_check", "layout_report", "spec_tuple"]

SHARD_RULES = {
    "SHD201": ("sharded-dim-not-divisible",
               "pad or reshape the dimension to a multiple of the mesh "
               "axis size, or shard a different dimension — XLA pads "
               "silently and a drifting remainder recompiles per shape"),
    "SHD202": ("implicit-reshard-hotspot",
               "an op boundary reshards more bytes than the threshold; "
               "move the sharding constraint, pre-reshard once outside "
               "the step, or change the layout so the contraction is "
               "local (this is the accidental all-gather-per-step that "
               "10x's step time)"),
    "SHD210": ("layout-report-drift",
               "the representative step's layout report no longer "
               "matches tools/layout_baseline.json; if the layout "
               "change is intentional run tools/lint.py "
               "--update-baseline, otherwise find the op that moved"),
}

_DEF_THRESHOLD = 1 << 20  # 1 MiB per boundary


# -- spec plumbing ------------------------------------------------------------
def spec_tuple(spec, ndim: int) -> Tuple:
    """Normalize a PartitionSpec / tuple / None to an ndim-length tuple
    whose entries are None, an axis name, or a tuple of axis names."""
    if spec is None:
        return (None,) * ndim
    if isinstance(spec, str):  # shorthand: one entry, not per-character
        spec = (spec,)
    entries = list(spec)
    entries = entries[:ndim] + [None] * (ndim - len(entries))
    out = []
    for e in entries:
        if e is None or isinstance(e, str):
            out.append(e)
        else:
            t = tuple(e)
            out.append(t if len(t) != 1 else t[0])
    return tuple(out)


def _axes_of(entry) -> Tuple[str, ...]:
    if entry is None:
        return ()
    if isinstance(entry, str):
        return (entry,)
    return tuple(entry)


def _factor(entry, mesh_axes: Dict[str, int]) -> int:
    n = 1
    for a in _axes_of(entry):
        n *= int(mesh_axes.get(a, 1))
    return n


def _spec_json(spec) -> List:
    return [list(_axes_of(e)) if not isinstance(e, (str, type(None)))
            else e for e in spec]


def _nbytes(aval) -> int:
    return int(math.prod(aval.shape)) * aval.dtype.itemsize


def _replicated(ndim: int) -> Tuple:
    return (None,) * ndim


# -- findings -----------------------------------------------------------------
def _finding(rule: str, message: str, label: str, line: int = 0) -> Finding:
    name, hint = SHARD_RULES[rule]
    return Finding(rule, label, line, 0, message, hint, "error")


def _check_divisible(shape, spec, mesh_axes, what, label,
                     findings: List[Finding]):
    for d, entry in enumerate(spec):
        k = _factor(entry, mesh_axes)
        if k > 1 and shape[d] % k != 0:
            findings.append(_finding(
                "SHD201",
                f"{what}: dim {d} (size {shape[d]}) is not divisible by "
                f"axes {list(_axes_of(entry))} (size {k}) — XLA pads to "
                f"{-(-shape[d] // k) * k} per device", label))


def _eqn_line(eqn) -> int:
    """Best-effort user source line for a jaxpr equation."""
    try:
        frame = eqn.source_info.traceback.frames[0]
        return int(frame.start_line)
    except Exception:
        return 0


# -- propagation --------------------------------------------------------------
class _Prop:
    def __init__(self, mesh_axes: Dict[str, int], label: str,
                 findings: List[Finding], ops: List[dict],
                 threshold: int = _DEF_THRESHOLD):
        self.mesh_axes = mesh_axes
        self.label = label
        self.findings = findings
        self.ops = ops
        self.threshold = int(threshold)
        self.total_bytes = 0

    def _record(self, eqn, out_spec, bytes_, note):
        self.total_bytes += bytes_
        aval = eqn.outvars[0].aval if eqn.outvars else None
        self.ops.append({
            "op": eqn.primitive.name,
            "shape": list(getattr(aval, "shape", ())),
            "spec": _spec_json(out_spec) if out_spec else [],
            "reshard_bytes": int(bytes_),
            "note": note,
        })

    def _merge(self, eqn, specs, avals):
        """Elementwise merge of operand specs (size-1 dims broadcast and
        carry no layout); a conflict — two different shardings of one
        dim — costs a reshard of the later operand."""
        out_shape = eqn.outvars[0].aval.shape
        bytes_ = 0
        notes = []
        out = [None] * len(out_shape)
        for spec, aval in zip(specs, avals):
            for d, (a, b) in enumerate(zip(out, spec)):
                if b is None or a == b or aval.shape[d] == 1:
                    continue
                if a is None:
                    out[d] = b
                else:
                    bytes_ += _nbytes(aval)
                    notes.append(f"dim {d}: {_axes_of(b)} -> {_axes_of(a)}")
        return tuple(out), bytes_, ("layout conflict: " + "; ".join(notes)
                                    if notes else "")

    def _dot_general(self, eqn, specs):
        (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
        lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
        ls, rs = specs
        bytes_ = 0
        notes = []
        for dl, dr in zip(lc, rc):
            al, ar = _axes_of(ls[dl]), _axes_of(rs[dr])
            if al and ar and al == ar:
                # both sides sharded alike: local dot + psum of the output
                out_b = _nbytes(eqn.outvars[0].aval)
                bytes_ += out_b
                notes.append(f"psum over {list(al)} ({out_b}B)")
            elif al:
                bytes_ += _nbytes(lhs)
                notes.append(f"all-gather lhs contracting dim {dl} "
                             f"({list(al)}, {_nbytes(lhs)}B)")
            elif ar:
                bytes_ += _nbytes(rhs)
                notes.append(f"all-gather rhs contracting dim {dr} "
                             f"({list(ar)}, {_nbytes(rhs)}B)")
        out_spec = tuple(
            [ls[d] for d in lb]
            + [ls[d] for d in range(lhs.ndim) if d not in lc + lb]
            + [rs[d] for d in range(rhs.ndim) if d not in rc + rb])
        return out_spec, bytes_, "; ".join(notes)

    def _reduce(self, eqn, spec):
        axes = eqn.params.get("axes", ())
        reduced = [a for d in axes for a in _axes_of(spec[d])]
        out_spec = tuple(e for d, e in enumerate(spec) if d not in axes)
        bytes_ = 0
        note = ""
        if reduced:
            bytes_ = _nbytes(eqn.outvars[0].aval)
            note = f"psum over {reduced} ({bytes_}B)"
        return out_spec, bytes_, note

    def run(self, jaxpr, env: Dict):
        from jax.core import Literal

        def read(v):
            if isinstance(v, Literal):
                return _replicated(getattr(v.aval, "ndim", 0))
            return env.get(v, _replicated(getattr(v.aval, "ndim", 0)))

        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            inner = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
            specs = [read(v) for v in eqn.invars]
            avals = [v.aval for v in eqn.invars]
            out_spec, bytes_, note = None, 0, ""
            if prim == "dot_general":
                out_spec, bytes_, note = self._dot_general(eqn, specs)
            elif prim.startswith("reduce_") and "axes" in eqn.params:
                out_spec, bytes_, note = self._reduce(eqn, specs[0])
            elif prim == "broadcast_in_dim":
                out_spec = list(_replicated(eqn.outvars[0].aval.ndim))
                for src, dst in enumerate(
                        eqn.params["broadcast_dimensions"]):
                    out_spec[dst] = specs[0][src]
                out_spec = tuple(out_spec)
            elif prim == "transpose":
                out_spec = tuple(specs[0][d]
                                 for d in eqn.params["permutation"])
            elif prim == "sharding_constraint":
                req = spec_tuple(
                    getattr(eqn.params.get("sharding"), "spec", None),
                    avals[0].ndim)
                _check_divisible(avals[0].shape, req, self.mesh_axes,
                                 f"sharding_constraint at line "
                                 f"{_eqn_line(eqn)}", self.label,
                                 self.findings)
                if specs[0] != req and any(e is not None for e in specs[0]):
                    bytes_ = _nbytes(avals[0])
                    note = (f"reshard {_spec_json(specs[0])} -> "
                            f"{_spec_json(req)}")
                out_spec = req
            elif inner is not None and prim in ("pjit", "custom_jvp_call",
                                                "custom_vjp_call",
                                                "custom_vjp_call_jaxpr",
                                                "remat", "checkpoint",
                                                "closed_call",
                                                "core_call", "xla_call"):
                sub = getattr(inner, "jaxpr", inner)
                sub_env = dict(zip(sub.invars, specs))
                self.run_sub(sub, sub_env)
                for outv, var in zip(eqn.outvars, sub.outvars):
                    env[outv] = sub_env.get(
                        var, _replicated(getattr(var.aval, "ndim", 0)))
                continue
            elif eqn.outvars and avals and all(
                    getattr(a, "ndim", -1) == 0
                    or (getattr(a, "ndim", -1) == eqn.outvars[0].aval.ndim
                        and all(s == o or s == 1 for s, o in
                                zip(a.shape, eqn.outvars[0].aval.shape)))
                    for a in avals):
                out_spec, bytes_, note = self._merge(eqn, specs, avals)
            else:
                # unknown structural op: layout knowledge stops here
                out_spec = None
                if any(any(e is not None for e in s) for s in specs):
                    note = "sharding dropped (unmodeled op)"
            for v in eqn.outvars:
                nd = getattr(v.aval, "ndim", 0)
                env[v] = (out_spec if out_spec is not None
                          and len(out_spec) == nd else _replicated(nd))
            self._record(eqn, env[eqn.outvars[0]] if eqn.outvars else (),
                         bytes_, note)
            if bytes_:
                line = _eqn_line(eqn)
                if bytes_ > self.threshold:
                    self.findings.append(_finding(
                        "SHD202",
                        f"op {prim!r} reshards ~{bytes_} bytes per step "
                        f"({note})", self.label, line))

    def run_sub(self, jaxpr, env):
        self.run(jaxpr, env)


# -- public API ---------------------------------------------------------------
def layout_check(fn, args: Sequence, in_specs: Sequence,
                 mesh_axes: Dict[str, int],
                 out_specs: Optional[Sequence] = None, *,
                 reshard_threshold: int = _DEF_THRESHOLD,
                 label: str = "layout_check"):
    """Abstractly evaluate `fn`'s layout. Returns (findings, report).

    args: flat sequence of arrays / ShapeDtypeStructs / (shape, dtype)
    tuples. in_specs: one PartitionSpec-like per arg. mesh_axes:
    {axis name: size} — no devices are required, the mesh is abstract.
    out_specs (optional): requested output layouts, checked against the
    propagated ones.
    """
    import jax
    import numpy as np

    structs = []
    for a in args:
        if isinstance(a, tuple) and len(a) == 2 and \
                not hasattr(a, "shape"):
            structs.append(jax.ShapeDtypeStruct(a[0], np.dtype(a[1])))
        else:
            structs.append(jax.ShapeDtypeStruct(a.shape, a.dtype))

    findings: List[Finding] = []
    ops: List[dict] = []
    specs = [spec_tuple(s, st.ndim) for s, st in zip(in_specs, structs)]
    for i, (st, sp) in enumerate(zip(structs, specs)):
        _check_divisible(st.shape, sp, mesh_axes, f"input {i}", label,
                         findings)

    # one abstract trace (eval_shape semantics: shapes only, no devices)
    closed = jax.make_jaxpr(fn)(*structs)
    jaxpr = closed.jaxpr

    prop = _Prop(dict(mesh_axes), label, findings, ops,
                 threshold=reshard_threshold)
    env = dict(zip(jaxpr.invars, specs))
    prop.run(jaxpr, env)

    out_leaves = [v for v in jaxpr.outvars]
    propagated = [env.get(v, _replicated(getattr(v.aval, "ndim", 0)))
                  for v in out_leaves]
    outputs = []
    for i, (v, got) in enumerate(zip(out_leaves, propagated)):
        nd = getattr(v.aval, "ndim", 0)
        rec = {"shape": list(getattr(v.aval, "shape", ())),
               "dtype": str(getattr(v.aval, "dtype", "?")),
               "spec": _spec_json(got)}
        if out_specs is not None and i < len(out_specs):
            want = spec_tuple(out_specs[i], nd)
            _check_divisible(v.aval.shape, want, mesh_axes,
                             f"output {i}", label, findings)
            rec["requested"] = _spec_json(want)
            if want != got and any(e is not None for e in got):
                b = _nbytes(v.aval)
                prop.total_bytes += b
                if b > prop.threshold:
                    findings.append(_finding(
                        "SHD202",
                        f"output {i} reshards ~{b} bytes to meet "
                        f"out_spec {_spec_json(want)} (propagated "
                        f"{_spec_json(got)})", label))
        outputs.append(rec)

    report = {
        "label": label,
        "mesh": {k: int(v) for k, v in mesh_axes.items()},
        "inputs": [{"shape": list(st.shape), "dtype": str(st.dtype),
                    "spec": _spec_json(sp)}
                   for st, sp in zip(structs, specs)],
        "outputs": outputs,
        "ops": ops,
        "total_reshard_bytes": int(prop.total_bytes),
        "violations": sorted(f.key() for f in findings),
    }
    return findings, report


def layout_report(fn, args, in_specs, mesh_axes, out_specs=None, **kw):
    """Just the JSON-ready report half of layout_check."""
    return layout_check(fn, args, in_specs, mesh_axes, out_specs, **kw)[1]


# the stable subset tools/lint.py diffs against tools/layout_baseline.json
# ("ops" is excluded: primitive spellings drift across jax versions)
BASELINE_KEYS = ("label", "mesh", "inputs", "outputs",
                 "total_reshard_bytes", "violations")


def baseline_view(report: dict) -> dict:
    return {k: report[k] for k in BASELINE_KEYS}
