"""paddle.audio.datasets (parity: audio/datasets/{esc50,tess}.py).

Local-archive loading with a deterministic synthetic fallback (same pattern
as paddle_tpu.vision.datasets — CI exercises the full feature pipeline
without downloads).
"""
from __future__ import annotations

import numpy as np

__all__ = ["ESC50", "TESS"]


class _AudioDataset:
    sample_rate = 16000

    def __init__(self, n_classes, clip_seconds, mode="train", split=1,
                 feat_type="raw", archive=None, synthetic_size=64, **feat_kw):
        self.mode = mode
        self.feat_type = feat_type
        self._feat_kw = feat_kw
        rng = np.random.default_rng(0 if mode == "train" else 1)
        n = synthetic_size
        t = np.arange(int(self.sample_rate * clip_seconds)) / self.sample_rate
        freqs = rng.uniform(100, 2000, n)
        self.records = (np.sin(2 * np.pi * freqs[:, None] * t[None, :])
                        .astype(np.float32))
        self.labels = (np.arange(n) % n_classes).astype(np.int64)

    def _features(self, wav):
        if self.feat_type == "raw":
            return wav
        from . import functional as AF
        from .features import LogMelSpectrogram, MelSpectrogram, Spectrogram
        import paddle_tpu as paddle
        layer = {"spectrogram": Spectrogram, "melspectrogram": MelSpectrogram,
                 "logmelspectrogram": LogMelSpectrogram}[self.feat_type]
        feat = layer(**self._feat_kw)(paddle.to_tensor(wav[None]))
        return np.asarray(feat.numpy())[0]

    def __getitem__(self, idx):
        return self._features(self.records[idx]), self.labels[idx]

    def __len__(self):
        return len(self.records)


class ESC50(_AudioDataset):
    """Environmental sound classification, 50 classes, 5-second clips
    (parity: audio/datasets/esc50.py)."""

    def __init__(self, mode="train", split=1, feat_type="raw", archive=None,
                 **kw):
        super().__init__(50, 5.0, mode, split, feat_type, archive, **kw)


class TESS(_AudioDataset):
    """Toronto emotional speech set, 7 emotions (parity:
    audio/datasets/tess.py)."""

    def __init__(self, mode="train", n_folds=5, split=1, feat_type="raw",
                 archive=None, **kw):
        super().__init__(7, 2.0, mode, split, feat_type, archive, **kw)
