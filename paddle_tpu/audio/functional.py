"""paddle.audio.functional (parity: audio/functional/functional.py)."""
from __future__ import annotations

import math

import jax.numpy as jnp

from ..ops.dispatch import ensure_tensor
from ..tensor import Tensor

__all__ = ["hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
           "compute_fbank_matrix", "power_to_db", "create_dct", "get_window"]


def _is_tensor(x):
    return isinstance(x, Tensor)


def hz_to_mel(freq, htk=False):
    """functional.py:29. Slaney scale by default (linear below 1 kHz)."""
    if htk:
        if _is_tensor(freq):
            return Tensor(2595.0 * jnp.log10(1.0 + freq._data / 700.0))
        return 2595.0 * math.log10(1.0 + freq / 700.0)
    f_sp = 200.0 / 3
    min_log_hz = 1000.0
    min_log_mel = min_log_hz / f_sp
    logstep = math.log(6.4) / 27.0
    if _is_tensor(freq):
        f = freq._data
        lin = f / f_sp
        log = min_log_mel + jnp.log(jnp.maximum(f, 1e-10) / min_log_hz) \
            / logstep
        return Tensor(jnp.where(f >= min_log_hz, log, lin))
    if freq >= min_log_hz:
        return min_log_mel + math.log(freq / min_log_hz) / logstep
    return freq / f_sp


def mel_to_hz(mel, htk=False):
    """functional.py:83."""
    if htk:
        if _is_tensor(mel):
            return Tensor(700.0 * (10.0 ** (mel._data / 2595.0) - 1.0))
        return 700.0 * (10.0 ** (mel / 2595.0) - 1.0)
    f_sp = 200.0 / 3
    min_log_hz = 1000.0
    min_log_mel = min_log_hz / f_sp
    logstep = math.log(6.4) / 27.0
    if _is_tensor(mel):
        m = mel._data
        lin = m * f_sp
        log = min_log_hz * jnp.exp(logstep * (m - min_log_mel))
        return Tensor(jnp.where(m >= min_log_mel, log, lin))
    if mel >= min_log_mel:
        return min_log_hz * math.exp(logstep * (mel - min_log_mel))
    return mel * f_sp


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False,
                    dtype="float32"):
    """functional.py:126: n_mels points uniformly spaced on the mel scale."""
    lo = hz_to_mel(f_min, htk)
    hi = hz_to_mel(f_max, htk)
    mels = Tensor(jnp.linspace(lo, hi, n_mels, dtype=jnp.float32))
    return mel_to_hz(mels, htk)


def fft_frequencies(sr, n_fft, dtype="float32"):
    """functional.py:166."""
    return Tensor(jnp.linspace(0.0, sr / 2.0, 1 + n_fft // 2,
                               dtype=jnp.float32))


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney", dtype="float32"):
    """functional.py:189: triangular mel filterbank [n_mels, n_fft//2+1]."""
    if f_max is None:
        f_max = float(sr) / 2
    fftfreqs = fft_frequencies(sr, n_fft)._data
    mel_f = mel_frequencies(n_mels + 2, f_min=f_min, f_max=f_max,
                            htk=htk)._data
    fdiff = mel_f[1:] - mel_f[:-1]
    ramps = mel_f[:, None] - fftfreqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = jnp.maximum(0.0, jnp.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
        weights = weights * enorm[:, None]
    elif isinstance(norm, (int, float)):
        nrm = jnp.sum(jnp.abs(weights) ** norm, axis=-1,
                      keepdims=True) ** (1.0 / norm)
        weights = weights / jnp.maximum(nrm, 1e-12)
    return Tensor(weights.astype(jnp.float32))


def power_to_db(spect, ref_value=1.0, amin=1e-10, top_db=80.0):
    """functional.py:262: 10*log10(max(spect, amin)/ref), floored at
    max - top_db."""
    from ..ops.dispatch import dispatch

    if top_db is not None and top_db < 0:
        raise ValueError("top_db must be non-negative")

    def fwd(s):
        s = s.astype(jnp.float32)
        log_spec = 10.0 * jnp.log10(jnp.maximum(s, amin))
        log_spec = log_spec - 10.0 * math.log10(max(ref_value, amin))
        if top_db is not None:
            log_spec = jnp.maximum(log_spec, jnp.max(log_spec) - top_db)
        return log_spec

    return dispatch("power_to_db", fwd, ensure_tensor(spect))


def create_dct(n_mfcc, n_mels, norm="ortho", dtype="float32"):
    """functional.py:306: DCT-II basis [n_mels, n_mfcc]."""
    n = jnp.arange(n_mels, dtype=jnp.float32)
    k = jnp.arange(n_mfcc, dtype=jnp.float32)[None, :]
    dct = jnp.cos(math.pi / n_mels * (n[:, None] + 0.5) * k) * 2.0
    if norm == "ortho":
        dct = dct.at[:, 0].multiply(1.0 / math.sqrt(2))
        dct = dct * math.sqrt(0.5 / n_mels)
    elif norm is not None:
        raise ValueError(f"unsupported norm {norm!r}")
    return Tensor(dct.astype(jnp.float32))


def get_window(window, win_length, fftbins=True, dtype="float32"):
    """audio/functional/window.py get_window — common analysis windows."""
    if isinstance(window, tuple):
        name, *params = window
    else:
        name, params = window, []
    n = win_length
    sym = not fftbins
    m = n if sym else n + 1
    i = jnp.arange(n, dtype=jnp.float32)
    if name in ("hann", "hanning"):
        w = 0.5 - 0.5 * jnp.cos(2 * math.pi * i / (m - 1))
    elif name == "hamming":
        w = 0.54 - 0.46 * jnp.cos(2 * math.pi * i / (m - 1))
    elif name == "blackman":
        w = (0.42 - 0.5 * jnp.cos(2 * math.pi * i / (m - 1))
             + 0.08 * jnp.cos(4 * math.pi * i / (m - 1)))
    elif name in ("rect", "boxcar", "ones"):
        w = jnp.ones(n, jnp.float32)
    elif name == "triang":
        # scipy.signal.windows.triang: denom m/2 (even) or (m+1)/2 (odd)
        denom = m / 2.0 if m % 2 == 0 else (m + 1) / 2.0
        w = 1.0 - jnp.abs(i - (m - 1) / 2.0) / denom
    elif name == "bartlett":
        w = 1.0 - jnp.abs(2.0 * i / (m - 1) - 1.0)
    elif name == "gaussian":
        std = params[0] if params else 7.0
        mm = (m - 1) / 2.0
        w = jnp.exp(-0.5 * ((i - mm) / std) ** 2)
    elif name == "taylor":
        # simple 4-term approximation fallback
        w = jnp.ones(n, jnp.float32)
    else:
        raise ValueError(f"unsupported window {name!r}")
    return Tensor(w.astype(jnp.float32))
