"""paddle.audio — audio feature extraction.

Reference parity: python/paddle/audio/ (functional/functional.py:29-306
hz_to_mel/mel_to_hz/mel_frequencies/fft_frequencies/compute_fbank_matrix/
power_to_db/create_dct, functional/window.py get_window, features/layers.py
Spectrogram/MelSpectrogram/LogMelSpectrogram/MFCC). TPU-native: everything
composes paddle_tpu.signal.stft (XLA FFT HLO) with jnp filterbank matmuls —
feature extraction runs inside jit with the model when desired.
"""
from . import datasets  # noqa: F401
from . import functional  # noqa: F401
from .features import (  # noqa: F401
    LogMelSpectrogram, MelSpectrogram, MFCC, Spectrogram,
)

__all__ = ["datasets", "functional", "features", "Spectrogram",
           "MelSpectrogram", "LogMelSpectrogram", "MFCC"]
from . import backends  # noqa: F401, E402
from .backends import info, load, save  # noqa: F401, E402
