"""paddle.audio.features (parity: audio/features/layers.py:47-346)."""
from __future__ import annotations

import jax.numpy as jnp

from .. import signal
from ..nn.layer.layers import Layer
from ..ops.dispatch import dispatch, ensure_tensor
from ..tensor import Tensor
from . import functional as AF

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


class Spectrogram(Layer):
    """STFT magnitude^power (layers.py:47). x: [B, T] -> [B, freq, frames]."""

    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 dtype="float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.window = AF.get_window(window, self.win_length)

    def forward(self, x):
        spec = signal.stft(x, self.n_fft, self.hop_length, self.win_length,
                           window=self.window, center=self.center,
                           pad_mode=self.pad_mode)

        def fwd(c):
            mag = jnp.abs(c)
            return (mag ** self.power).astype(jnp.float32)

        return dispatch("spectrogram_mag", fwd, ensure_tensor(spec))


class MelSpectrogram(Layer):
    """Spectrogram -> mel filterbank (layers.py:132)."""

    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 dtype="float32"):
        super().__init__()
        self._spectrogram = Spectrogram(n_fft, hop_length, win_length, window,
                                        power, center, pad_mode)
        self.fbank = AF.compute_fbank_matrix(sr, n_fft, n_mels, f_min, f_max,
                                             htk, norm)

    def forward(self, x):
        spec = self._spectrogram(x)                     # [B, freq, frames]
        fb = self.fbank

        def fwd(s, w):
            return jnp.einsum("mf,...ft->...mt", w, s)

        return dispatch("mel_fbank", fwd, ensure_tensor(spec),
                        ensure_tensor(fb))


class LogMelSpectrogram(Layer):
    """MelSpectrogram in dB (layers.py:239)."""

    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 ref_value=1.0, amin=1e-10, top_db=None, dtype="float32"):
        super().__init__()
        self._melspectrogram = MelSpectrogram(sr, n_fft, hop_length,
                                              win_length, window, power,
                                              center, pad_mode, n_mels,
                                              f_min, f_max, htk, norm)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        mel = self._melspectrogram(x)
        return AF.power_to_db(mel, self.ref_value, self.amin, self.top_db)


class MFCC(Layer):
    """Mel-frequency cepstral coefficients (layers.py:346)."""

    def __init__(self, sr=22050, n_mfcc=40, norm="ortho", **mel_kwargs):
        super().__init__()
        self._log_melspectrogram = LogMelSpectrogram(sr, **mel_kwargs)
        n_mels = self._log_melspectrogram._melspectrogram.fbank.shape[0]
        self.dct = AF.create_dct(n_mfcc, int(n_mels), norm)

    def forward(self, x):
        log_mel = self._log_melspectrogram(x)           # [B, n_mels, T]
        d = self.dct

        def fwd(s, w):
            return jnp.einsum("mk,...mt->...kt", w, s)

        return dispatch("mfcc_dct", fwd, ensure_tensor(log_mel),
                        ensure_tensor(d))
