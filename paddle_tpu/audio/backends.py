"""paddle.audio.backends (reference python/paddle/audio/backends/):
wave-file IO. The 'wave' backend is stdlib-based (16/32-bit PCM WAV read
+ write) — the reference's soundfile backend is an optional extra there
too, and this image ships no soundfile."""
from __future__ import annotations

import wave as _wave
from dataclasses import dataclass

import numpy as np


@dataclass
class AudioInfo:
    """Parity: backend info() result (sample rate, frames, channels,
    bits per sample)."""
    sample_rate: int
    num_samples: int
    num_channels: int
    bits_per_sample: int
    encoding: str = "PCM_S"


def list_available_backends():
    """Parity: paddle.audio.backends.list_available_backends."""
    return ["wave_backend"]


def get_current_backend():
    return "wave_backend"


def set_backend(backend_name: str):
    if backend_name != "wave_backend":
        raise NotImplementedError(
            f"backend {backend_name!r}: only the stdlib wave backend is "
            "available in this image (soundfile is not installed)")


def info(filepath: str) -> AudioInfo:
    """Parity: paddle.audio.info."""
    with _wave.open(str(filepath), "rb") as w:
        return AudioInfo(sample_rate=w.getframerate(),
                         num_samples=w.getnframes(),
                         num_channels=w.getnchannels(),
                         bits_per_sample=8 * w.getsampwidth())


def load(filepath: str, frame_offset: int = 0, num_frames: int = -1,
         normalize: bool = True, channels_first: bool = True):
    """Parity: paddle.audio.load — returns (waveform Tensor, sample_rate).
    normalize=True scales PCM to [-1, 1] float32."""
    import jax.numpy as jnp

    from ..tensor import Tensor
    with _wave.open(str(filepath), "rb") as w:
        sr = w.getframerate()
        nch = w.getnchannels()
        width = w.getsampwidth()
        w.setpos(min(frame_offset, w.getnframes()))
        n = w.getnframes() - frame_offset if num_frames < 0 else num_frames
        raw = w.readframes(max(n, 0))
    dt = {1: np.uint8, 2: np.int16, 4: np.int32}.get(width)
    if dt is None:
        raise ValueError(f"unsupported WAV sample width {width}")
    data = np.frombuffer(raw, dt).reshape(-1, nch)
    if normalize:
        if dt == np.uint8:       # unsigned 8-bit PCM centers at 128
            out = (data.astype(np.float32) - 128.0) / 128.0
        else:
            out = data.astype(np.float32) / float(2 ** (8 * width - 1))
    else:
        out = data               # raw PCM samples, untouched
    if channels_first:
        out = out.T
    return Tensor(jnp.asarray(np.ascontiguousarray(out))), sr


def save(filepath: str, src, sample_rate: int, channels_first: bool = True,
         encoding: str = "PCM_16", bits_per_sample: int = 16):
    """Parity: paddle.audio.save — float waveform in [-1, 1] to 16-bit
    PCM WAV."""
    arr = np.asarray(src.numpy() if hasattr(src, "numpy") else src)
    if arr.ndim == 1:
        arr = arr[None] if channels_first else arr[:, None]
    if channels_first:
        arr = arr.T                               # -> [frames, channels]
    if bits_per_sample != 16:
        raise NotImplementedError("the wave backend writes 16-bit PCM")
    pcm = np.clip(np.round(arr.astype(np.float64) * 32767), -32768,
                  32767).astype("<i2")
    with _wave.open(str(filepath), "wb") as w:
        w.setnchannels(arr.shape[1])
        w.setsampwidth(2)
        w.setframerate(int(sample_rate))
        w.writeframes(pcm.tobytes())


__all__ = ["AudioInfo", "list_available_backends", "get_current_backend",
           "set_backend", "info", "load", "save"]
