"""paddle_tpu.sysconfig — include/lib directories for extension builds.

Reference parity: python/paddle/sysconfig.py (get_include/get_lib point
at the installed package's headers and shared libraries). Here they point
at the package's native artifacts (csrc headers, _native shared objects)
consumed by utils.cpp_extension."""
from __future__ import annotations

import os

_ROOT = os.path.dirname(os.path.abspath(__file__))


def get_include() -> str:
    """Directory containing the native C headers/sources (csrc/)."""
    return os.path.join(_ROOT, "csrc")


def get_lib() -> str:
    """Directory containing the built native shared libraries."""
    return os.path.join(_ROOT, "_native")


__all__ = ["get_include", "get_lib"]
