"""paddle.geometric — graph learning ops.

Reference parity: python/paddle/geometric/ (message_passing/send_recv.py
send_u_recv :55 / send_ue_recv :210 / send_uv :413; sampling/neighbors.py
sample_neighbors :30; reindex.py reindex_graph :34; plus the segment ops).
TPU-native: message passing is gather + scatter-reduce (`.at[].add/max/min`),
which XLA lowers to fused scatters; sampling/reindexing are host-side eager
ops (data-dependent shapes), matching the reference's CPU kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops.dispatch import dispatch, ensure_tensor
from ..tensor import Tensor
from ..incubate.segment_ops import (  # noqa: F401
    segment_max, segment_mean, segment_min, segment_sum,
)

__all__ = ["send_u_recv", "send_ue_recv", "send_uv", "sample_neighbors",
           "reindex_graph", "reindex_heter_graph", "segment_sum",
           "segment_mean", "segment_max", "segment_min",
           "weighted_sample_neighbors"]


def _host_rng():
    """Host-side numpy RNG seeded from the framework RNG stream, so
    paddle.seed() makes graph sampling reproducible (parity: the reference
    samplers draw from the global generator)."""
    import numpy as np

    from ..framework.random import next_key
    seed = int(jax.random.randint(next_key(), (), 0, 2 ** 31 - 1))
    return np.random.default_rng(seed)


def _resolve_out_size(out_size, dst_arr):
    if out_size is None:
        return None
    if isinstance(out_size, Tensor):
        out_size = int(out_size.numpy())
    out_size = int(out_size)
    return out_size if out_size > 0 else None


def _scatter_reduce(msgs, dst, n_out, reduce_op, dtype):
    shape = (n_out,) + msgs.shape[1:]
    if reduce_op == "sum" or reduce_op == "mean":
        out = jnp.zeros(shape, jnp.float32).at[dst].add(
            msgs.astype(jnp.float32))
        if reduce_op == "mean":
            cnt = jnp.zeros((n_out,), jnp.float32).at[dst].add(1.0)
            out = out / jnp.maximum(cnt, 1.0).reshape(
                (n_out,) + (1,) * (msgs.ndim - 1))
        return out.astype(dtype)
    if reduce_op == "max":
        init = jnp.finfo(jnp.float32).min
    elif reduce_op == "min":
        init = jnp.finfo(jnp.float32).max
    else:
        raise ValueError(f"unknown reduce_op {reduce_op!r}")
    out = jnp.full(shape, init, jnp.float32)
    out = (out.at[dst].max(msgs.astype(jnp.float32)) if reduce_op == "max"
           else out.at[dst].min(msgs.astype(jnp.float32)))
    # untouched rows are 0 (reference fills missing destinations with 0)
    touched = jnp.zeros((n_out,), jnp.bool_).at[dst].set(True)
    out = jnp.where(touched.reshape((n_out,) + (1,) * (msgs.ndim - 1)),
                    out, 0.0)
    return out.astype(dtype)


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather x[src] then scatter-reduce at dst (send_recv.py:55)."""
    xt = ensure_tensor(x)
    st, dt = ensure_tensor(src_index), ensure_tensor(dst_index)
    n_out = _resolve_out_size(out_size, dt) or int(xt.shape[0])

    def fwd(xa, src, dst):
        msgs = xa[src.astype(jnp.int32)]
        return _scatter_reduce(msgs, dst.astype(jnp.int32), n_out, reduce_op,
                               xa.dtype)

    return dispatch("send_u_recv", fwd, xt, st, dt)


def _message(msg_op, xe, y):
    y = y.astype(jnp.float32)
    xe = xe.astype(jnp.float32)
    while y.ndim < xe.ndim:
        y = y[..., None]
    if msg_op == "add":
        return xe + y
    if msg_op == "sub":
        return xe - y
    if msg_op == "mul":
        return xe * y
    if msg_op == "div":
        return xe / y
    raise ValueError(f"unknown message_op {msg_op!r}")


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Gather x[src], combine with the per-edge feature y, scatter-reduce at
    dst (send_recv.py:210)."""
    xt, yt = ensure_tensor(x), ensure_tensor(y)
    st, dt = ensure_tensor(src_index), ensure_tensor(dst_index)
    n_out = _resolve_out_size(out_size, dt) or int(xt.shape[0])

    def fwd(xa, ya, src, dst):
        msgs = _message(message_op, xa[src.astype(jnp.int32)], ya)
        return _scatter_reduce(msgs, dst.astype(jnp.int32), n_out, reduce_op,
                               xa.dtype)

    return dispatch("send_ue_recv", fwd, xt, yt, st, dt)


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Edge features from source/destination node features
    (send_recv.py:413): out[e] = x[src[e]] (op) y[dst[e]]."""
    xt, yt = ensure_tensor(x), ensure_tensor(y)
    st, dt = ensure_tensor(src_index), ensure_tensor(dst_index)

    def fwd(xa, ya, src, dst):
        out = _message(message_op, xa[src.astype(jnp.int32)],
                       ya[dst.astype(jnp.int32)])
        return out.astype(xa.dtype)

    return dispatch("send_uv", fwd, xt, yt, st, dt)


def _sample_csc(row, colptr, input_nodes, sample_size, eids, return_eids,
                weights):
    """Shared CSC neighbor sampler (uniform when weights is None)."""
    import numpy as np

    rows = np.asarray(ensure_tensor(row).numpy()).reshape(-1)
    cptr = np.asarray(ensure_tensor(colptr).numpy()).reshape(-1)
    nodes = np.asarray(ensure_tensor(input_nodes).numpy()).reshape(-1)
    wts = (np.asarray(ensure_tensor(weights).numpy()).reshape(-1)
           if weights is not None else None)
    eid_arr = (np.asarray(ensure_tensor(eids).numpy()).reshape(-1)
               if eids is not None else None)
    if return_eids and eid_arr is None:
        raise ValueError("return_eids=True requires eids")
    rng = _host_rng()
    out_n, out_c, out_e = [], [], []
    for v in nodes:
        beg, end = int(cptr[v]), int(cptr[v + 1])
        if sample_size < 0 or end - beg <= sample_size:
            pick = np.arange(end - beg)
        else:
            pr = None
            if wts is not None:
                w = wts[beg:end].astype(np.float64)
                pr = w / w.sum()
            pick = rng.choice(end - beg, size=sample_size, replace=False,
                              p=pr)
        out_n.append(rows[beg:end][pick])
        out_c.append(len(pick))
        if eid_arr is not None:
            out_e.append(eid_arr[beg:end][pick])
    neighbors = Tensor(jnp.asarray(np.concatenate(out_n)
                                   if out_n else np.zeros(0, rows.dtype)))
    counts = Tensor(jnp.asarray(np.asarray(out_c, np.int32)))
    if return_eids:
        return neighbors, counts, Tensor(jnp.asarray(np.concatenate(out_e)))
    return neighbors, counts


def sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                     return_eids=False, perm_buffer=None, name=None):
    """Uniform neighbor sampling over a CSC graph (neighbors.py:30).

    Host-side eager op (data-dependent output size, like the reference CPU
    kernel). Returns (out_neighbors, out_count[, out_eids])."""
    return _sample_csc(row, colptr, input_nodes, sample_size, eids,
                       return_eids, None)


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None, return_eids=False,
                              name=None):
    """Weighted neighbor sampling (weighted_sample_neighbors op): neighbors
    drawn without replacement with probability proportional to edge weight."""
    return _sample_csc(row, colptr, input_nodes, sample_size, eids,
                       return_eids, edge_weight)


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Reindex sampled subgraph node ids from 0 (reindex.py:34). Returns
    (reindex_src, reindex_dst, out_nodes)."""
    import numpy as np

    xs = np.asarray(ensure_tensor(x).numpy()).reshape(-1)
    nb = np.asarray(ensure_tensor(neighbors).numpy()).reshape(-1)
    ct = np.asarray(ensure_tensor(count).numpy()).reshape(-1)
    mapping = {}
    out_nodes = []
    for v in xs:
        if int(v) not in mapping:
            mapping[int(v)] = len(out_nodes)
            out_nodes.append(int(v))
    src = np.empty(len(nb), np.int64)
    for i, v in enumerate(nb):
        vi = int(v)
        if vi not in mapping:
            mapping[vi] = len(out_nodes)
            out_nodes.append(vi)
        src[i] = mapping[vi]
    dst = np.repeat(np.arange(len(xs)), ct)
    dtype = nb.dtype
    return (Tensor(jnp.asarray(src.astype(dtype))),
            Tensor(jnp.asarray(dst.astype(dtype))),
            Tensor(jnp.asarray(np.asarray(out_nodes, dtype))))


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """Parity: geometric.reindex_heter_graph (reindex.py heterogeneous
    form): `neighbors`/`count` are per-edge-type lists sampled for the
    SAME seed set x; one shared id space reindexes all types. Returns
    (reindex_src, reindex_dst, out_nodes) with src/dst concatenated in
    edge-type order."""
    import numpy as np

    xs = np.asarray(ensure_tensor(x).numpy()).reshape(-1)
    mapping = {}
    out_nodes = []
    for v in xs:
        if int(v) not in mapping:
            mapping[int(v)] = len(out_nodes)
            out_nodes.append(int(v))
    srcs = []
    dsts = []
    dtype = None
    for nbr, cnt in zip(neighbors, count):
        nb = np.asarray(ensure_tensor(nbr).numpy()).reshape(-1)
        ct = np.asarray(ensure_tensor(cnt).numpy()).reshape(-1)
        dtype = nb.dtype if dtype is None else dtype
        src = np.empty(len(nb), np.int64)
        for i, v in enumerate(nb):
            vi = int(v)
            if vi not in mapping:
                mapping[vi] = len(out_nodes)
                out_nodes.append(vi)
            src[i] = mapping[vi]
        srcs.append(src)
        dsts.append(np.repeat(np.arange(len(xs)), ct))
    src_all = np.concatenate(srcs) if srcs else np.zeros(0, np.int64)
    dst_all = np.concatenate(dsts) if dsts else np.zeros(0, np.int64)
    return (Tensor(jnp.asarray(src_all.astype(dtype or np.int64))),
            Tensor(jnp.asarray(dst_all.astype(dtype or np.int64))),
            Tensor(jnp.asarray(np.asarray(out_nodes,
                                          dtype or np.int64))))
