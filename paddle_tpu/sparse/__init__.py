"""Sparse tensors and ops.

Reference parity: python/paddle/sparse/ (sparse_coo_tensor,
sparse_csr_tensor, to_dense/to_sparse_coo/to_sparse_csr, unary ops, add,
matmul, masked_matmul; C++ SparseCooTensor/SparseCsrTensor in
phi/core/sparse_*_tensor.h, kernels phi/kernels/sparse/).

TPU-native: XLA has no sparse storage, so sparse tensors are coordinate
lists (indices + values as dense arrays) and the ops lower to
gather/scatter/segment-sum HLOs — the standard JAX sparse recipe (a BCOO
analog). Values stay differentiable; structure (indices) is static data.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp

from ..ops.dispatch import dispatch, ensure_tensor
from ..tensor import Tensor


class SparseCooTensor:
    """COO: indices [ndim, nnz] (int), values [nnz, ...dense_dims]."""

    def __init__(self, indices, values, shape, coalesced: bool = False):
        self.indices = ensure_tensor(indices)
        self.values = ensure_tensor(values)
        self._shape = [int(s) for s in shape]
        self._coalesced = coalesced

    @property
    def shape(self):
        return list(self._shape)

    @property
    def dtype(self):
        return self.values.dtype

    def nnz(self) -> int:
        return int(self.indices._data.shape[1])

    @property
    def stop_gradient(self):
        return self.values.stop_gradient

    @stop_gradient.setter
    def stop_gradient(self, v):
        self.values.stop_gradient = v

    def to_dense(self) -> Tensor:
        shape = tuple(self._shape)
        nd = self.indices._data.shape[0]

        def fwd(idx, vals):
            dense = jnp.zeros(shape[:nd] + vals.shape[1:], vals.dtype)
            return dense.at[tuple(idx)].add(vals)
        return dispatch("sparse_to_dense", fwd, self.indices, self.values)

    def coalesce(self) -> "SparseCooTensor":
        """Merge duplicate coordinates (sum values)."""
        idx = self.indices._data
        vals = self.values._data
        nd = idx.shape[0]
        flat = jnp.ravel_multi_index(tuple(idx), tuple(self._shape[:nd]),
                                     mode="clip")
        uniq, pos = jnp.unique(flat, return_inverse=True)
        merged = jnp.zeros((uniq.shape[0],) + vals.shape[1:], vals.dtype) \
            .at[pos].add(vals)
        new_idx = jnp.stack(jnp.unravel_index(uniq, tuple(self._shape[:nd])))
        return SparseCooTensor(Tensor(new_idx), Tensor(merged), self._shape,
                               coalesced=True)

    def transpose(self, perm) -> "SparseCooTensor":
        idx = self.indices._data[jnp.asarray(perm)]
        shape = [self._shape[p] for p in perm]
        return SparseCooTensor(Tensor(idx), self.values, shape)

    def __repr__(self):
        return (f"SparseCooTensor(shape={self._shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


class SparseCsrTensor:
    """CSR (2-D): crows [rows+1], cols [nnz], values [nnz]."""

    def __init__(self, crows, cols, values, shape):
        self.crows = ensure_tensor(crows)
        self.cols = ensure_tensor(cols)
        self.values = ensure_tensor(values)
        self._shape = [int(s) for s in shape]

    @property
    def shape(self):
        return list(self._shape)

    @property
    def dtype(self):
        return self.values.dtype

    def nnz(self) -> int:
        return int(self.cols._data.shape[0])

    def _row_indices(self):
        crows = self.crows._data
        counts = crows[1:] - crows[:-1]
        return jnp.repeat(jnp.arange(self._shape[0]), counts,
                          total_repeat_length=self.nnz())

    def to_dense(self) -> Tensor:
        rows = self._row_indices()
        shape = tuple(self._shape)

        def fwd(cols, vals):
            dense = jnp.zeros(shape, vals.dtype)
            return dense.at[rows, cols].add(vals)
        return dispatch("csr_to_dense", fwd, self.cols, self.values)

    def to_sparse_coo(self, sparse_dim: int = 2) -> SparseCooTensor:
        rows = self._row_indices()
        idx = jnp.stack([rows, self.cols._data])
        return SparseCooTensor(Tensor(idx), self.values, self._shape)

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self._shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True) -> SparseCooTensor:
    it = ensure_tensor(indices)
    vt = ensure_tensor(values, dtype=dtype)
    if shape is None:
        maxes = jnp.max(it._data, axis=1) + 1
        shape = [int(m) for m in maxes] + list(vt._data.shape[1:])
    t = SparseCooTensor(it, vt, shape)
    t.stop_gradient = stop_gradient
    return t


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True) -> SparseCsrTensor:
    t = SparseCsrTensor(ensure_tensor(crows), ensure_tensor(cols),
                        ensure_tensor(values, dtype=dtype), shape)
    t.values.stop_gradient = stop_gradient
    return t


def to_sparse_coo(dense: Tensor, sparse_dim: Optional[int] = None):
    """Dense -> COO over the leading `sparse_dim` dims (default: all).

    The coordinate pattern is data (extracted eagerly); the values gather
    goes through dispatch so gradients flow back into the dense input."""
    dt = ensure_tensor(dense)
    arr = dt._data
    nd = sparse_dim or arr.ndim
    lead = arr.reshape(arr.shape[:nd] + (-1,))
    mask = jnp.any(lead != 0, axis=-1)
    idx = jnp.stack(jnp.nonzero(mask))
    vals = dispatch("coo_values_gather", lambda a: a[tuple(idx)], dt)
    return SparseCooTensor(Tensor(idx), vals, list(arr.shape))


def to_sparse_csr(dense: Tensor) -> SparseCsrTensor:
    arr = ensure_tensor(dense)._data
    assert arr.ndim == 2, "CSR is 2-D"
    rows, cols = jnp.nonzero(arr != 0)
    vals = arr[rows, cols]
    crows = jnp.zeros(arr.shape[0] + 1, jnp.int32).at[rows + 1].add(1)
    crows = jnp.cumsum(crows)
    return SparseCsrTensor(Tensor(crows), Tensor(cols), Tensor(vals),
                           list(arr.shape))


def _unary(name, jnp_fn):
    """Zero-preserving unary op applied to values only (reference
    phi/kernels/sparse/unary_kernel pattern)."""
    def op(x):
        if isinstance(x, SparseCooTensor):
            out = dispatch(f"sparse_{name}", jnp_fn, x.values)
            return SparseCooTensor(x.indices, out, x.shape)
        if isinstance(x, SparseCsrTensor):
            out = dispatch(f"sparse_{name}", jnp_fn, x.values)
            return SparseCsrTensor(x.crows, x.cols, out, x.shape)
        raise TypeError(f"sparse.{name} expects a sparse tensor")
    op.__name__ = name
    return op


relu = _unary("relu", jax.nn.relu)
abs = _unary("abs", jnp.abs)
sin = _unary("sin", jnp.sin)
sinh = _unary("sinh", jnp.sinh)
asin = _unary("asin", jnp.arcsin)
asinh = _unary("asinh", jnp.arcsinh)
atan = _unary("atan", jnp.arctan)
atanh = _unary("atanh", jnp.arctanh)
tan = _unary("tan", jnp.tan)
tanh = _unary("tanh", jnp.tanh)
sqrt = _unary("sqrt", jnp.sqrt)
square = _unary("square", jnp.square)
neg = _unary("neg", jnp.negative)
expm1 = _unary("expm1", jnp.expm1)
log1p = _unary("log1p", jnp.log1p)
deg2rad = _unary("deg2rad", jnp.deg2rad)
rad2deg = _unary("rad2deg", jnp.rad2deg)
isnan = _unary("isnan", jnp.isnan)


def pow(x, factor, name=None):  # noqa: A001 - parity name
    """Element-wise power on the stored values (zero-preserving for
    factor > 0, matching the reference sparse pow)."""
    f = float(factor)
    return _unary("pow", lambda v: jnp.power(v, f))(x)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    from ..framework.dtype import convert_dtype
    vd = convert_dtype(value_dtype) if value_dtype is not None else None
    idd = convert_dtype(index_dtype) if index_dtype is not None else None
    if isinstance(x, SparseCooTensor):
        idx = (Tensor(x.indices._data.astype(idd)) if idd else x.indices)
        vals = (Tensor(x.values._data.astype(vd)) if vd else x.values)
        return SparseCooTensor(idx, vals, x.shape)
    if isinstance(x, SparseCsrTensor):
        crows = (Tensor(x.crows._data.astype(idd)) if idd else x.crows)
        cols = (Tensor(x.cols._data.astype(idd)) if idd else x.cols)
        vals = (Tensor(x.values._data.astype(vd)) if vd else x.values)
        return SparseCsrTensor(crows, cols, vals, x.shape)
    raise TypeError("sparse.cast expects a sparse tensor")


def coalesce(x, name=None):
    if not isinstance(x, SparseCooTensor):
        raise TypeError("sparse.coalesce expects a COO tensor")
    return x.coalesce()


def transpose(x, perm, name=None):
    if isinstance(x, SparseCsrTensor):
        x = x.to_sparse_coo()
    return x.transpose(perm)


def reshape(x, shape, name=None):
    """COO reshape via flat-coordinate remapping over the SPARSE dims; the
    trailing dense dims (hybrid COO) must be unchanged by the new shape."""
    if isinstance(x, SparseCsrTensor):
        x = x.to_sparse_coo()
    nd = x.indices._data.shape[0]
    old_sparse = tuple(x.shape[:nd])
    dense_tail = list(x.shape[nd:])
    shape = list(shape)
    total_sparse = 1
    for d in old_sparse:
        total_sparse *= d
    if -1 in shape:
        known = 1
        for d in shape:
            if d != -1:
                known *= d
        total_all = total_sparse
        for d in dense_tail:
            total_all *= d
        shape[shape.index(-1)] = total_all // known
    if dense_tail:
        if shape[len(shape) - len(dense_tail):] != dense_tail:
            raise ValueError(
                f"sparse.reshape on a hybrid COO tensor must keep the dense "
                f"tail {dense_tail} unchanged, got {shape}")
        new_sparse = tuple(shape[:len(shape) - len(dense_tail)])
    else:
        new_sparse = tuple(shape)
    flat = jnp.ravel_multi_index(tuple(x.indices._data), old_sparse,
                                 mode="clip")
    new_idx = jnp.stack(jnp.unravel_index(flat, new_sparse))
    return SparseCooTensor(Tensor(new_idx), x.values, shape)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):  # noqa: A001
    """Sum over one axis; full reduction returns a dense scalar Tensor.
    Negative axes are normalized by the TENSOR rank; a dense-tail axis of a
    hybrid COO tensor reduces the values array directly."""
    if isinstance(x, SparseCsrTensor):
        x = x.to_sparse_coo()
    if axis is None:
        from ..ops import math as M
        return M.sum(x.values)
    nd = x.indices._data.shape[0]
    rank = len(x.shape)
    ax = axis if axis >= 0 else axis + rank
    if ax >= nd:
        # dense-tail axis: values dim (ax - nd + 1); structure unchanged
        vax = ax - nd + 1
        vals = jnp.sum(x.values._data.astype(jnp.float32), axis=vax,
                       keepdims=keepdim).astype(x.values._data.dtype)
        shp = list(x.shape)
        if keepdim:
            shp[ax] = 1
        else:
            shp.pop(ax)
        return SparseCooTensor(x.indices, Tensor(vals), shp)
    keep = [d for d in range(nd) if d != ax]
    new_idx = x.indices._data[jnp.asarray(keep)]
    new_shape = [x.shape[d] for d in keep] + list(x.shape[nd:])
    out = SparseCooTensor(Tensor(new_idx), x.values, new_shape).coalesce()
    if keepdim:
        exp = jnp.insert(out.indices._data, ax, 0, axis=0)
        shp = list(out.shape)
        shp.insert(ax, 1)
        return SparseCooTensor(Tensor(exp), out.values, shp)
    return out


def slice(x, axes, starts, ends, name=None):  # noqa: A001 - parity name
    """COO slice: host-filtered coordinates (eager; structure is data)."""
    import numpy as np
    if isinstance(x, SparseCsrTensor):
        x = x.to_sparse_coo()
    idx = np.asarray(x.indices.numpy())
    vals_keep = np.ones(idx.shape[1], bool)
    new_shape = list(x.shape)
    shifts = np.zeros(idx.shape[0], np.int64)
    for ax, st, en in zip(axes, starts, ends):
        size = x.shape[ax]
        st = max(st + size, 0) if st < 0 else min(st, size)
        en = max(en + size, 0) if en < 0 else min(en, size)
        vals_keep &= (idx[ax] >= st) & (idx[ax] < en)
        new_shape[ax] = max(en - st, 0)
        shifts[ax] = st
    sel = np.nonzero(vals_keep)[0]
    new_idx = idx[:, sel] - shifts[:, None]
    return SparseCooTensor(Tensor(jnp.asarray(new_idx)),
                           Tensor(x.values._data[jnp.asarray(sel)]),
                           new_shape)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """PCA of a sparse matrix (parity: paddle.sparse.pca_lowrank). Lowers to
    a dense SVD — XLA has no sparse factorization, and q is typically small."""
    dense = x.to_dense() if not isinstance(x, Tensor) else x
    a = dense._data.astype(jnp.float32)
    if q is None:
        q = min(6, a.shape[-2], a.shape[-1])
    if center:
        a = a - jnp.mean(a, axis=-2, keepdims=True)
    u, s_, vt = jnp.linalg.svd(a, full_matrices=False)
    return (Tensor(u[..., :q]), Tensor(s_[..., :q]),
            Tensor(jnp.swapaxes(vt, -1, -2)[..., :q]))


def add(x, y):
    """sparse+sparse (same shape) -> sparse; sparse+dense -> dense."""
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        idx = jnp.concatenate([x.indices._data, y.indices._data], axis=1)
        from ..ops.manipulation import concat
        vals = concat([x.values, y.values], axis=0)
        return SparseCooTensor(Tensor(idx), vals, x.shape).coalesce()
    if isinstance(x, SparseCooTensor):
        return x.to_dense() + ensure_tensor(y)
    raise TypeError("sparse.add expects sparse x")


def matmul(x, y) -> Tensor:
    """sparse [m, k] @ dense [k, n] -> dense [m, n] via gather +
    segment-sum (XLA's sparse-matmul recipe)."""
    if isinstance(x, SparseCsrTensor):
        x = x.to_sparse_coo()
    if not isinstance(x, SparseCooTensor):
        raise TypeError("sparse.matmul expects sparse x")
    yt = ensure_tensor(y)
    m = x.shape[0]
    rows = x.indices._data[0]
    cols = x.indices._data[1]

    def fwd(vals, dense):
        gathered = vals[:, None] * dense[cols]           # [nnz, n]
        return jax.ops.segment_sum(gathered, rows, num_segments=m)
    return dispatch("sparse_matmul", fwd, x.values, yt)


def masked_matmul(x: Tensor, y: Tensor, mask) -> SparseCooTensor:
    """dense @ dense evaluated only at `mask`'s coordinates (SDDMM)."""
    if not isinstance(mask, (SparseCooTensor, SparseCsrTensor)):
        raise TypeError("mask must be sparse")
    coo = mask.to_sparse_coo() if isinstance(mask, SparseCsrTensor) else mask
    xt, yt = ensure_tensor(x), ensure_tensor(y)
    rows = coo.indices._data[0]
    cols = coo.indices._data[1]

    def fwd(a, b):
        return (a[rows] * b[:, cols].T).sum(-1)
    vals = dispatch("masked_matmul", fwd, xt, yt)
    return SparseCooTensor(coo.indices, vals, coo.shape)


def is_same_shape(x, y) -> bool:
    return list(x.shape) == list(y.shape)


def _coo_binary(name, x, y, fn):
    """Elementwise sparse-sparse op via the union of coordinates (reference
    sparse elementwise kernels); zero-fill for coordinates present in only
    one operand."""
    if not (isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor)):
        raise TypeError(f"sparse.{name} expects two COO tensors")
    xc, yc = x.coalesce(), y.coalesce()
    nd = xc.indices._data.shape[0]
    shape = tuple(xc.shape[:nd])
    fx = jnp.ravel_multi_index(tuple(xc.indices._data), shape, mode="clip")
    fy = jnp.ravel_multi_index(tuple(yc.indices._data), shape, mode="clip")
    uni = jnp.unique(jnp.concatenate([fx, fy]))
    n = uni.shape[0]
    vx = (jnp.zeros((n,) + xc.values._data.shape[1:], jnp.float32)
          .at[jnp.searchsorted(uni, fx)]
          .set(xc.values._data.astype(jnp.float32)))
    vy = (jnp.zeros((n,) + yc.values._data.shape[1:], jnp.float32)
          .at[jnp.searchsorted(uni, fy)]
          .set(yc.values._data.astype(jnp.float32)))
    vals = fn(vx, vy).astype(xc.values._data.dtype)
    idx = jnp.stack(jnp.unravel_index(uni, shape))
    return SparseCooTensor(Tensor(idx), Tensor(vals), x.shape,
                           coalesced=True)


def subtract(x, y, name=None):
    return _coo_binary("subtract", x, y, lambda a, b: a - b)


def multiply(x, y, name=None):
    return _coo_binary("multiply", x, y, lambda a, b: a * b)


def divide(x, y, name=None):
    return _coo_binary("divide", x, y, lambda a, b: a / b)


def mv(x, vec, name=None):
    """sparse [m, k] @ dense [k] -> dense [m]."""
    out = matmul(x, ensure_tensor(vec).reshape([-1, 1]))
    return out.reshape([-1])


def mask_as(x, mask, name=None):
    """Take dense x's values at `mask`'s coordinates."""
    coo = mask.to_sparse_coo() if isinstance(mask, SparseCsrTensor) else mask
    xt = ensure_tensor(x)
    idx = coo.indices._data

    def fwd(a):
        return a[tuple(idx)]

    vals = dispatch("mask_as", fwd, xt)
    return SparseCooTensor(coo.indices, vals, coo.shape)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """beta * input + alpha * (x @ y) with sparse x (parity:
    paddle.sparse.addmm)."""
    prod = matmul(x, y)
    it = input.to_dense() if isinstance(
        input, (SparseCooTensor, SparseCsrTensor)) else ensure_tensor(input)
    from ..ops import math as M
    return M.add(M.scale(it, beta), M.scale(prod, alpha))


from . import nn  # noqa: E402,F401 (sparse.nn layer package)
