"""Sparse tensors and ops.

Reference parity: python/paddle/sparse/ (sparse_coo_tensor,
sparse_csr_tensor, to_dense/to_sparse_coo/to_sparse_csr, unary ops, add,
matmul, masked_matmul; C++ SparseCooTensor/SparseCsrTensor in
phi/core/sparse_*_tensor.h, kernels phi/kernels/sparse/).

TPU-native: XLA has no sparse storage, so sparse tensors are coordinate
lists (indices + values as dense arrays) and the ops lower to
gather/scatter/segment-sum HLOs — the standard JAX sparse recipe (a BCOO
analog). Values stay differentiable; structure (indices) is static data.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp

from ..ops.dispatch import dispatch, ensure_tensor
from ..tensor import Tensor


class SparseCooTensor:
    """COO: indices [ndim, nnz] (int), values [nnz, ...dense_dims]."""

    def __init__(self, indices, values, shape, coalesced: bool = False):
        self.indices = ensure_tensor(indices)
        self.values = ensure_tensor(values)
        self._shape = [int(s) for s in shape]
        self._coalesced = coalesced

    @property
    def shape(self):
        return list(self._shape)

    @property
    def dtype(self):
        return self.values.dtype

    def nnz(self) -> int:
        return int(self.indices._data.shape[1])

    @property
    def stop_gradient(self):
        return self.values.stop_gradient

    @stop_gradient.setter
    def stop_gradient(self, v):
        self.values.stop_gradient = v

    def to_dense(self) -> Tensor:
        shape = tuple(self._shape)
        nd = self.indices._data.shape[0]

        def fwd(idx, vals):
            dense = jnp.zeros(shape[:nd] + vals.shape[1:], vals.dtype)
            return dense.at[tuple(idx)].add(vals)
        return dispatch("sparse_to_dense", fwd, self.indices, self.values)

    def coalesce(self) -> "SparseCooTensor":
        """Merge duplicate coordinates (sum values)."""
        idx = self.indices._data
        vals = self.values._data
        nd = idx.shape[0]
        flat = jnp.ravel_multi_index(tuple(idx), tuple(self._shape[:nd]),
                                     mode="clip")
        uniq, pos = jnp.unique(flat, return_inverse=True)
        merged = jnp.zeros((uniq.shape[0],) + vals.shape[1:], vals.dtype) \
            .at[pos].add(vals)
        new_idx = jnp.stack(jnp.unravel_index(uniq, tuple(self._shape[:nd])))
        return SparseCooTensor(Tensor(new_idx), Tensor(merged), self._shape,
                               coalesced=True)

    def transpose(self, perm) -> "SparseCooTensor":
        idx = self.indices._data[jnp.asarray(perm)]
        shape = [self._shape[p] for p in perm]
        return SparseCooTensor(Tensor(idx), self.values, shape)

    def __repr__(self):
        return (f"SparseCooTensor(shape={self._shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


class SparseCsrTensor:
    """CSR (2-D): crows [rows+1], cols [nnz], values [nnz]."""

    def __init__(self, crows, cols, values, shape):
        self.crows = ensure_tensor(crows)
        self.cols = ensure_tensor(cols)
        self.values = ensure_tensor(values)
        self._shape = [int(s) for s in shape]

    @property
    def shape(self):
        return list(self._shape)

    @property
    def dtype(self):
        return self.values.dtype

    def nnz(self) -> int:
        return int(self.cols._data.shape[0])

    def _row_indices(self):
        crows = self.crows._data
        counts = crows[1:] - crows[:-1]
        return jnp.repeat(jnp.arange(self._shape[0]), counts,
                          total_repeat_length=self.nnz())

    def to_dense(self) -> Tensor:
        rows = self._row_indices()
        shape = tuple(self._shape)

        def fwd(cols, vals):
            dense = jnp.zeros(shape, vals.dtype)
            return dense.at[rows, cols].add(vals)
        return dispatch("csr_to_dense", fwd, self.cols, self.values)

    def to_sparse_coo(self, sparse_dim: int = 2) -> SparseCooTensor:
        rows = self._row_indices()
        idx = jnp.stack([rows, self.cols._data])
        return SparseCooTensor(Tensor(idx), self.values, self._shape)

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self._shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True) -> SparseCooTensor:
    it = ensure_tensor(indices)
    vt = ensure_tensor(values, dtype=dtype)
    if shape is None:
        maxes = jnp.max(it._data, axis=1) + 1
        shape = [int(m) for m in maxes] + list(vt._data.shape[1:])
    t = SparseCooTensor(it, vt, shape)
    t.stop_gradient = stop_gradient
    return t


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True) -> SparseCsrTensor:
    t = SparseCsrTensor(ensure_tensor(crows), ensure_tensor(cols),
                        ensure_tensor(values, dtype=dtype), shape)
    t.values.stop_gradient = stop_gradient
    return t


def to_sparse_coo(dense: Tensor, sparse_dim: Optional[int] = None):
    """Dense -> COO over the leading `sparse_dim` dims (default: all)."""
    dt = ensure_tensor(dense)
    arr = dt._data
    nd = sparse_dim or arr.ndim
    lead = arr.reshape(arr.shape[:nd] + (-1,))
    mask = jnp.any(lead != 0, axis=-1)
    idx = jnp.stack(jnp.nonzero(mask))
    vals = arr[tuple(idx)]
    return SparseCooTensor(Tensor(idx), Tensor(vals), list(arr.shape))


def to_sparse_csr(dense: Tensor) -> SparseCsrTensor:
    arr = ensure_tensor(dense)._data
    assert arr.ndim == 2, "CSR is 2-D"
    rows, cols = jnp.nonzero(arr != 0)
    vals = arr[rows, cols]
    crows = jnp.zeros(arr.shape[0] + 1, jnp.int32).at[rows + 1].add(1)
    crows = jnp.cumsum(crows)
    return SparseCsrTensor(Tensor(crows), Tensor(cols), Tensor(vals),
                           list(arr.shape))


def _unary(name, jnp_fn):
    """Zero-preserving unary op applied to values only (reference
    phi/kernels/sparse/unary_kernel pattern)."""
    def op(x):
        if isinstance(x, SparseCooTensor):
            out = dispatch(f"sparse_{name}", jnp_fn, x.values)
            return SparseCooTensor(x.indices, out, x.shape)
        if isinstance(x, SparseCsrTensor):
            out = dispatch(f"sparse_{name}", jnp_fn, x.values)
            return SparseCsrTensor(x.crows, x.cols, out, x.shape)
        raise TypeError(f"sparse.{name} expects a sparse tensor")
    op.__name__ = name
    return op


relu = _unary("relu", jax.nn.relu)
abs = _unary("abs", jnp.abs)
sin = _unary("sin", jnp.sin)
tanh = _unary("tanh", jnp.tanh)
sqrt = _unary("sqrt", jnp.sqrt)
neg = _unary("neg", jnp.negative)
expm1 = _unary("expm1", jnp.expm1)
log1p = _unary("log1p", jnp.log1p)
pow = _unary("square", jnp.square)  # noqa: A001 - parity name


def add(x, y):
    """sparse+sparse (same shape) -> sparse; sparse+dense -> dense."""
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        idx = jnp.concatenate([x.indices._data, y.indices._data], axis=1)
        from ..ops.manipulation import concat
        vals = concat([x.values, y.values], axis=0)
        return SparseCooTensor(Tensor(idx), vals, x.shape).coalesce()
    if isinstance(x, SparseCooTensor):
        return x.to_dense() + ensure_tensor(y)
    raise TypeError("sparse.add expects sparse x")


def matmul(x, y) -> Tensor:
    """sparse [m, k] @ dense [k, n] -> dense [m, n] via gather +
    segment-sum (XLA's sparse-matmul recipe)."""
    if isinstance(x, SparseCsrTensor):
        x = x.to_sparse_coo()
    if not isinstance(x, SparseCooTensor):
        raise TypeError("sparse.matmul expects sparse x")
    yt = ensure_tensor(y)
    m = x.shape[0]
    rows = x.indices._data[0]
    cols = x.indices._data[1]

    def fwd(vals, dense):
        gathered = vals[:, None] * dense[cols]           # [nnz, n]
        return jax.ops.segment_sum(gathered, rows, num_segments=m)
    return dispatch("sparse_matmul", fwd, x.values, yt)


def masked_matmul(x: Tensor, y: Tensor, mask) -> SparseCooTensor:
    """dense @ dense evaluated only at `mask`'s coordinates (SDDMM)."""
    if not isinstance(mask, (SparseCooTensor, SparseCsrTensor)):
        raise TypeError("mask must be sparse")
    coo = mask.to_sparse_coo() if isinstance(mask, SparseCsrTensor) else mask
    xt, yt = ensure_tensor(x), ensure_tensor(y)
    rows = coo.indices._data[0]
    cols = coo.indices._data[1]

    def fwd(a, b):
        return (a[rows] * b[:, cols].T).sum(-1)
    vals = dispatch("masked_matmul", fwd, xt, yt)
    return SparseCooTensor(coo.indices, vals, coo.shape)


def is_same_shape(x, y) -> bool:
    return list(x.shape) == list(y.shape)
