"""paddle.sparse.nn — layers over sparse tensors.

Reference parity: python/paddle/sparse/nn/ (layer/activation.py, conv.py,
norm.py, pooling.py; kernels phi/kernels/sparse/ conv_kernel etc.).

TPU-native notes: activations/norms act on the dense `values` array of the
COO tensor (same as the reference kernels). The conv family lowers to a
dense XLA convolution and re-sparsifies — XLA has no sparse gather-gemm
conv; for submanifold convs the output keeps the input's coordinate set,
matching SubmConv semantics exactly.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..nn.layer.layers import Layer
from ..tensor import Tensor


def _values_map(x, fn, name):
    from . import SparseCooTensor, SparseCsrTensor
    from ..ops.dispatch import dispatch
    if isinstance(x, SparseCooTensor):
        return SparseCooTensor(x.indices, dispatch(name, fn, x.values),
                               x.shape)
    if isinstance(x, SparseCsrTensor):
        return SparseCsrTensor(x.crows, x.cols, dispatch(name, fn, x.values),
                               x.shape)
    raise TypeError(f"sparse.nn.{name} expects a sparse tensor")


class ReLU(Layer):
    def forward(self, x):
        return _values_map(x, jax.nn.relu, "sparse_relu")


class ReLU6(Layer):
    def forward(self, x):
        return _values_map(x, lambda v: jnp.clip(v, 0, 6), "sparse_relu6")


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self._slope = float(negative_slope)

    def forward(self, x):
        s = self._slope
        return _values_map(x, lambda v: jnp.where(v >= 0, v, s * v),
                           "sparse_leaky_relu")


class Softmax(Layer):
    """Row-wise softmax over the stored values of a 2-D CSR matrix
    (parity: sparse/nn/layer/activation.py Softmax, axis=-1 only)."""

    def __init__(self, axis=-1, name=None):
        super().__init__()
        if axis != -1:
            raise ValueError("sparse Softmax supports axis=-1 only")

    def forward(self, x):
        from . import SparseCsrTensor
        from ..ops.dispatch import dispatch
        if not isinstance(x, SparseCsrTensor):
            raise TypeError("sparse Softmax expects a CSR tensor")
        rows = x._row_indices()
        n_rows = x.shape[0]

        def fwd(vals):
            v = vals.astype(jnp.float32)
            mx = jnp.full((n_rows,), jnp.finfo(jnp.float32).min) \
                .at[rows].max(v)
            e = jnp.exp(v - mx[rows])
            den = jnp.zeros((n_rows,), jnp.float32).at[rows].add(e)
            return (e / den[rows]).astype(vals.dtype)

        return SparseCsrTensor(x.crows, x.cols, dispatch("sparse_softmax",
                                                         fwd, x.values),
                               x.shape)


class BatchNorm(Layer):
    """BatchNorm over the channel (last) axis of COO values (parity:
    sparse/nn/layer/norm.py BatchNorm — input layout [N, ..., C] sparse)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__()
        from ..nn import BatchNorm1D
        self._bn = BatchNorm1D(num_features, momentum=momentum,
                               epsilon=epsilon, weight_attr=weight_attr,
                               bias_attr=bias_attr)

    def forward(self, x):
        from . import SparseCooTensor
        if not isinstance(x, SparseCooTensor):
            raise TypeError("sparse BatchNorm expects a COO tensor")
        out_vals = self._bn(x.values)
        return SparseCooTensor(x.indices, out_vals, x.shape)


class SyncBatchNorm(BatchNorm):
    """Single-process alias; cross-replica stats are subsumed by GSPMD when
    the values array is batch-sharded inside a compiled step."""


class MaxPool3D(Layer):
    """Sparse NDHWC max pooling via dense lowering (values re-sparsified
    with the pooled nonzero pattern)."""

    def __init__(self, kernel_size, stride=None, padding=0, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding

    def forward(self, x):
        from . import SparseCooTensor, to_sparse_coo
        from ..nn import functional as F
        if not isinstance(x, SparseCooTensor):
            raise TypeError("sparse MaxPool3D expects a COO tensor")
        dense = x.to_dense()  # [N, D, H, W, C]
        out = F.max_pool3d(dense.transpose([0, 4, 1, 2, 3]),
                           self.kernel_size, self.stride, self.padding)
        out = out.transpose([0, 2, 3, 4, 1])
        return to_sparse_coo(out, sparse_dim=4)


class _SparseConvNd(Layer):
    """Shared dense-lowered sparse conv (NDHWC / NHWC layouts)."""

    def __init__(self, in_channels, out_channels, kernel_size, nd,
                 stride=1, padding=0, dilation=1, groups=1, subm=False,
                 padding_mode="zeros", weight_attr=None, bias_attr=None,
                 data_format=None, name=None):
        super().__init__()
        self._nd = nd
        self._subm = subm
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.groups = groups
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,) * nd
        # paddle sparse conv weight layout: [*kernel, in/groups, out]
        self.weight = self.create_parameter(
            tuple(kernel_size) + (in_channels // groups, out_channels),
            attr=weight_attr)
        self.bias = (None if bias_attr is False else
                     self.create_parameter((out_channels,), attr=bias_attr,
                                           is_bias=True))

    def forward(self, x):
        from . import SparseCooTensor, to_sparse_coo
        from ..nn import functional as F
        from ..ops.manipulation import transpose as tr
        if not isinstance(x, SparseCooTensor):
            raise TypeError("sparse conv expects a COO tensor")
        nd = self._nd
        dense = x.to_dense()                      # [N, *spatial, C]
        perm_in = [0, nd + 1] + list(range(1, nd + 1))
        perm_w = [nd + 1, nd] + list(range(nd))   # -> [out, in/g, *kernel]
        conv = F.conv3d if nd == 3 else F.conv2d
        out = conv(tr(dense, perm_in), tr(self.weight, perm_w),
                   bias=self.bias, stride=self.stride, padding=self.padding,
                   dilation=self.dilation, groups=self.groups)
        perm_out = [0] + list(range(2, nd + 2)) + [1]
        out = tr(out, perm_out)                   # [N, *spatial, C]
        if self._subm:
            # submanifold: output keeps the input's coordinate set
            from . import mask_as
            ref = SparseCooTensor(x.indices,
                                  Tensor(jnp.ones((x.nnz(),), jnp.float32)),
                                  list(out.shape))
            return mask_as(out, ref)
        return to_sparse_coo(out, sparse_dim=nd + 1)


class Conv2D(_SparseConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NHWC",
                 name=None):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, subm=False,
                         weight_attr=weight_attr, bias_attr=bias_attr)


class Conv3D(_SparseConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 name=None):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, subm=False,
                         weight_attr=weight_attr, bias_attr=bias_attr)


class SubmConv2D(_SparseConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 key=None, weight_attr=None, bias_attr=None,
                 data_format="NHWC", name=None):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, subm=True,
                         weight_attr=weight_attr, bias_attr=bias_attr)


class SubmConv3D(_SparseConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 key=None, weight_attr=None, bias_attr=None,
                 data_format="NDHWC", name=None):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, subm=True,
                         weight_attr=weight_attr, bias_attr=bias_attr)
